"""Fleet observability plane (ml_trainer_tpu/telemetry/federation.py +
the router's fleet plane in serving/router.py).

The pure federation/merge core is pinned with golden text and synthetic
multi-pid payloads (fast, no processes); the router-side plumbing —
scrape, re-label, aggregate ``/healthz``, trace context over the wire,
incident bundles — is pinned with in-process servers behind REAL HTTP
sockets (the test_fleet.py idiom: the socket is real, the processes are
not).  The true multi-process run lives in scripts/fleet_obs_smoke.py
and the bench gate's gate_fleet observability invariants.
"""

import json
import os
import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import Router, Server
from ml_trainer_tpu.serving.fleet import RemoteServer
from ml_trainer_tpu.telemetry import compile_watch, federation, spans
from ml_trainer_tpu.telemetry.export import sink_path_for_worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- federation: pure text-rewrite core -----------------------------------

WORKER_TEXT = (
    "# HELP serving_requests_total requests\n"
    "# TYPE serving_requests_total counter\n"
    'serving_requests_total{tenant="a"} 3\n'
    "# HELP ttft_ms time to first token\n"
    "# TYPE ttft_ms histogram\n"
    'ttft_ms_bucket{le="1"} 2\n'
    'ttft_ms_bucket{le="+Inf"} 4\n'
    "ttft_ms_sum 5.5\n"
    "ttft_ms_count 4\n"
    "# HELP compile_events_post_warmup_total recompiles\n"
    "# TYPE compile_events_post_warmup_total counter\n"
    "compile_events_post_warmup_total 0\n"
)
BASE_TEXT = (
    "# HELP router_inflight in flight\n"
    "# TYPE router_inflight gauge\n"
    "router_inflight 2\n"
)


def test_inject_labels_shapes():
    extra = {"replica": "d0", "role": "decode", "generation": 1}
    assert federation.inject_labels('up 1', extra) == (
        'up{replica="d0",role="decode",generation="1"} 1'
    )
    assert federation.inject_labels('x{tenant="a"} 3', extra) == (
        'x{tenant="a",replica="d0",role="decode",generation="1"} 3'
    )
    # Existing labels win — never a duplicated label name.
    assert federation.inject_labels('x{replica="w"} 1', extra) == (
        'x{replica="w",role="decode",generation="1"} 1'
    )
    # Comments and blanks pass through untouched.
    assert federation.inject_labels("# HELP x y", extra) == "# HELP x y"
    assert federation.inject_labels("", extra) == ""


def test_federate_exposition_golden_shape():
    fed = federation.federate_exposition(BASE_TEXT, [
        (WORKER_TEXT, {"replica": "p0", "role": "prefill",
                       "generation": 0}),
        (WORKER_TEXT, {"replica": "d0", "role": "decode",
                       "generation": 0}),
    ])
    lines = fed.splitlines()
    # One HELP/TYPE header per family, first writer wins.
    assert lines.count("# TYPE serving_requests_total counter") == 1
    assert lines.count("# TYPE ttft_ms histogram") == 1
    # Both replicas' samples present, labels injected.
    for rep, role in (("p0", "prefill"), ("d0", "decode")):
        assert (
            f'serving_requests_total{{tenant="a",replica="{rep}",'
            f'role="{role}",generation="0"}} 3'
        ) in lines
        assert (
            f'compile_events_post_warmup_total{{replica="{rep}",'
            f'role="{role}",generation="0"}} 0'
        ) in lines
    # Histogram children stay grouped under their family's one TYPE
    # header (no second "# TYPE ttft_ms" anywhere after samples).
    idx = lines.index("# TYPE ttft_ms histogram")
    block = lines[idx + 1:idx + 9]
    assert sum(
        1 for ln in block if ln.startswith("ttft_ms_bucket{")
    ) == 4
    # The router's own series survive unlabeled.
    assert "router_inflight 2" in lines


def test_federate_rerender_idempotent():
    """Rendering twice from the same snapshots returns the same bytes —
    the replace-never-accumulate property that makes re-scraping safe
    (a histogram count is what the worker last reported, not a running
    sum of scrapes)."""
    sections = [
        (WORKER_TEXT, {"replica": "p0", "role": "prefill",
                       "generation": 0}),
    ]
    a = federation.federate_exposition(BASE_TEXT, sections)
    b = federation.federate_exposition(BASE_TEXT, sections)
    assert a == b
    assert a.count('ttft_ms_count{replica="p0"') == 1


def test_resolve_clock_shift():
    # No estimate at all: visible, not a guess.
    assert federation.resolve_clock_shift(None, None, None) == (
        None, "none"
    )
    assert federation.resolve_clock_shift(42.0, None, None) == (
        42.0, "epoch"
    )
    assert federation.resolve_clock_shift(None, 17.0, 100.0) == (
        17.0, "ntp"
    )
    # Agreement within rtt/2 + slack: shared clock -> exact epoch shift.
    shift, method = federation.resolve_clock_shift(1000.0, 990.0, 200.0)
    assert (shift, method) == (1000.0, "epoch")
    # Disagreement: distinct clocks -> trust the handshake.
    shift, method = federation.resolve_clock_shift(
        50_000.0, 100.0, 200.0
    )
    assert (shift, method) == (100.0, "ntp")


def test_merge_fleet_trace_lanes_and_causal_order():
    """Synthetic multi-pid merge: a migrated request's prefill fragment
    (worker A's epoch) must land BEFORE its decode span (worker B's
    epoch) on the merged clock — per-process shifts applied, one lane
    per pid, every lane named."""
    local = [{
        "name": "kv_wire 7", "ph": "X", "ts": 900.0, "dur": 50.0,
        "pid": 100, "tid": 1, "args": {},
    }]
    remotes = [
        {
            "name": "p0",
            "payload": {"pid": 200, "events": [{
                "name": "request 7 (prefill)", "ph": "X", "ts": 10.0,
                "dur": 500.0, "pid": 200, "tid": 1, "args": {},
            }]},
            "epoch_shift_us": 400.0, "ntp_shift_us": 395.0,
            "rtt_us": 100.0,
        },
        {
            "name": "d0",
            "payload": {"pid": 300, "events": [{
                "name": "request 7", "ph": "X", "ts": 5.0, "dur": 400.0,
                "pid": 300, "tid": 1, "args": {},
            }]},
            "epoch_shift_us": 1000.0, "ntp_shift_us": 998.0,
            "rtt_us": 80.0,
        },
    ]
    merged = federation.merge_fleet_trace(local, "router", 100, remotes)
    events = merged["traceEvents"]
    lanes = {e["pid"] for e in events if e.get("ph") != "M"}
    assert lanes == {100, 200, 300}
    names = {
        e["args"]["name"] for e in events if e.get("ph") == "M"
    }
    assert names == {"router", "p0", "d0"}
    pre = next(e for e in events
               if e["name"] == "request 7 (prefill)")
    dec = next(e for e in events
               if e["name"] == "request 7" and e["pid"] == 300)
    assert pre["ts"] == pytest.approx(410.0)   # 10 + epoch shift 400
    assert dec["ts"] == pytest.approx(1005.0)  # 5 + epoch shift 1000
    assert dec["ts"] >= pre["ts"] + pre["dur"]  # causal on ONE clock
    assert merged["fleetClock"]["p0"]["method"] == "epoch"
    assert merged["fleetClock"]["d0"]["method"] == "epoch"
    assert merged["fleetClock"]["router"]["method"] == "local"
    # The source payloads were not mutated by the shift.
    assert remotes[0]["payload"]["events"][0]["ts"] == 10.0


def test_merge_fleet_trace_no_clock_is_visible_not_dropped():
    merged = federation.merge_fleet_trace([], "router", 1, [{
        "name": "w0",
        "payload": {"pid": 2, "events": [{
            "name": "x", "ph": "X", "ts": 123.0, "dur": 1.0, "pid": 2,
            "tid": 1,
        }]},
        "epoch_shift_us": None, "ntp_shift_us": None, "rtt_us": None,
    }])
    assert merged["fleetClock"]["w0"]["method"] == "none"
    ev = next(e for e in merged["traceEvents"] if e.get("name") == "x")
    assert ev["ts"] == 123.0  # unshifted, lane still present


def test_sink_path_for_worker():
    assert sink_path_for_worker("/x/m.jsonl", "decode0") == (
        "/x/m.decode0.jsonl"
    )
    assert sink_path_for_worker("/x/metrics", "w1") == "/x/metrics.w1"


# -- router-side plumbing over real sockets -------------------------------

@pytest.fixture(scope="module")
def socket_fleet():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    compile_watch.install()  # workers install it; here: shared process
    servers, remotes = {}, {}
    router = None
    try:
        for name, role in (("prefill0", "prefill"),
                           ("decode0", "decode")):
            srv = Server(model, variables, max_batch=2, kv_page_size=8,
                         role=role, prefill_chunk=16)
            srv.name = name
            host, port = srv.serve_http(port=0)
            servers[name] = srv
            remotes[name] = RemoteServer(
                f"http://{host}:{port}", name=name
            )
        router = Router(
            dict(remotes),
            replica_urls={n: r.url for n, r in remotes.items()},
            hedging=False, metrics_scrape_interval=0.05,
            incident_min_interval_s=30.0,
        )
        yield model, variables, servers, router
    finally:
        if router is not None:
            router.close()
        for srv in servers.values():
            srv.close()


def test_federated_scrape_labels_and_idempotency(socket_fleet):
    model, variables, servers, router = socket_fleet
    p = np.random.default_rng(0).integers(0, 1024, 24).astype(np.int32)
    ref = np.asarray(generate(model, variables, p[None], 8))[0]
    out = np.asarray(router.complete(p, 8, timeout=120))
    np.testing.assert_array_equal(out, ref)
    # Warm render first: the router's publish() registers its series
    # in the (shared, in this in-process setup) registry, and the
    # workers' scraped text must settle before the idempotency pair.
    router.federated_metrics_text()
    router.scrape_metrics(force=True)
    fed = router.federated_metrics_text()

    def worker_lines(text):
        # The in-process servers share the router's registry, so the
        # router's own router_* series leak into the scraped "worker"
        # text and grow as the router publishes between renders; filter
        # them to the worker-owned families (a real fleet worker has
        # its own process registry — the multi-process idempotency is
        # pinned by scripts/fleet_obs_smoke.py and gate_fleet).
        return [ln for ln in text.splitlines()
                if ln and not ln.startswith(("#", "router_"))
                and 'replica="' in ln]

    lines = worker_lines(fed)
    for name, role in (("prefill0", "prefill"), ("decode0", "decode")):
        assert any(
            ln.startswith("compile_events_post_warmup_total{")
            and f'replica="{name}"' in ln and f'role="{role}"' in ln
            and 'generation="0"' in ln
            for ln in lines
        ), f"{name}'s post-warmup counter missing from the federation"
    # Worker histograms present WITH labels (the exposition stays one
    # valid document — child samples grouped under their family).
    assert any(
        "_bucket{" in ln and 'replica="' in ln for ln in lines
    )
    # Re-scrape + re-render until quiescent: consecutive renders become
    # identical (snapshots replace — a histogram cannot double-count
    # across scrapes; an accumulate bug would grow EVERY re-scrape and
    # never stabilise).  Gauges are excluded, and the request's late
    # async bookkeeping (the in-process worker's TTFT observation can
    # land ms after the stream returns) is absorbed by the settle loop.
    def counting_lines(lns):
        return [ln for ln in lns if "_bucket{" in ln or "_sum{" in ln
                or "_count{" in ln or "_total{" in ln]

    def rescrape():
        router.scrape_metrics(force=True)
        return counting_lines(worker_lines(router.federated_metrics_text()))

    prev, deadline = rescrape(), time.monotonic() + 15.0
    while time.monotonic() < deadline:
        cur = rescrape()
        if cur == prev:
            break
        prev = cur
    assert rescrape() == prev


def test_aggregated_healthz_names_fleet_keys(socket_fleet):
    _, _, _, router = socket_fleet
    for rep in router.replicas.values():
        rep.last_health = rep.fetch_health()
    hz = router.health()
    for name, h in hz["replicas"].items():
        assert "compile_events_post_warmup_total" in h, name
        assert "degradation_level" in h, name
        assert h["compile_events_post_warmup_total"] == 0
    # The worker health payload itself carries the clock handshake
    # fields the poller's NTP estimate needs.
    raw = router.replicas["decode0"].last_health
    assert "trace_now_us" in raw and "mono_epoch" in raw


def test_scrape_error_bumps_counter_not_poller(socket_fleet):
    _, _, _, router = socket_fleet
    rep = router.replicas["decode0"]
    good_url, good_text = rep.url, rep.metrics_text
    before = router.snapshot()["scrape_errors_total"].get("decode0", 0)
    try:
        rep.url = "http://127.0.0.1:9"  # discard port: nothing listens
        router.scrape_metrics(force=True)  # must not raise
    finally:
        rep.url = good_url
    snap = router.snapshot()
    assert snap["scrape_errors_total"]["decode0"] == before + 1
    # The last good snapshot is kept — the federation does not lose
    # the replica's section while it flaps.
    assert rep.metrics_text == good_text


def test_trace_context_rides_the_socket_wire(socket_fleet):
    """A client trace id survives router -> prefill -> adopt -> decode
    across real HTTP hops: the prefill-side fragment, the router's
    kv_wire span, and the decode-side request span all carry it (the
    in-process servers share this process's span buffer, so the whole
    causal chain is visible locally)."""
    model, variables, _, router = socket_fleet
    p = np.random.default_rng(1).integers(0, 1024, 24).astype(np.int32)
    ref = np.asarray(generate(model, variables, p[None], 8))[0]
    out = np.asarray(router.complete(
        p, 8, timeout=120, trace={"trace_id": 424242},
    ))
    np.testing.assert_array_equal(out, ref)
    names = [e.get("name", "") for e in spans.trace_events()]
    assert "request 424242 (prefill)" in names
    assert "kv_wire 424242" in names
    assert "request 424242" in names


def test_incident_bundle_contents_and_throttle(socket_fleet, tmp_path):
    _, _, _, router = socket_fleet
    out = str(tmp_path)
    b1 = router.save_incident_bundle("unit: first", out_dir=out)
    assert b1 is not None
    have = set(os.listdir(b1))
    assert {"flight_router.json", "flight_prefill0.json",
            "flight_decode0.json", "slo_timelines.json", "metrics.prom",
            "router.json", "manifest.json"} <= have
    manifest = json.load(open(os.path.join(b1, "manifest.json")))
    assert manifest["reason"] == "unit: first"
    assert set(manifest["replica_flights"]) == {"prefill0", "decode0"}
    # The manifest inventories every artifact written BEFORE itself.
    assert set(manifest["files"]) == have - {"manifest.json"}
    flight = json.load(open(os.path.join(b1, "flight_decode0.json")))
    assert flight.get("reason") == "fleet_fetch"
    prom = open(os.path.join(b1, "metrics.prom")).read()
    assert 'replica="decode0"' in prom
    # Throttled: a flapping replica must not write one bundle per poll.
    assert router.save_incident_bundle("unit: second") is None
    b3 = router.save_incident_bundle("unit: third", out_dir=out,
                                     force=True)
    assert b3 is not None and b3 != b1
    assert router.last_incident_path == b3
    assert router.snapshot()["incidents_total"] >= 2
