"""Continuous-batching serving engine (ml_trainer_tpu/serving/).

Ground truth is ``generate()``: a request served through the slot engine
— joining and leaving a running batch at arbitrary token boundaries —
must reproduce its standalone batch-1 ``generate()`` output
byte-for-byte, greedy and seeded-sampling alike.  Around that core:
slot recycling on EOS, admission backpressure, deadlines, metrics, and
the stdlib HTTP front end.
"""

import os
import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import (
    AdmissionError,
    DeadlineExceeded,
    Server,
)


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


def test_join_mid_decode_matches_generate_token_for_token(model_and_vars):
    """The acceptance scenario: two requests submitted MID-STREAM of a
    running decode; all three outputs byte-identical to standalone
    generate() calls (greedy and seeded sampling)."""
    model, variables = model_and_vars
    pA, pB, pC = _prompt(0, 5), _prompt(1, 3), _prompt(2, 7)
    refA = np.asarray(generate(model, variables, pA[None], 24))[0]
    refB = np.asarray(generate(model, variables, pB[None], 8))[0]
    refC = np.asarray(
        generate(model, variables, pC[None], 8, temperature=0.7,
                 rng=jax.random.PRNGKey(42))
    )[0]

    with Server(model, variables, max_batch=4) as server:
        sA = server.submit(pA, 24)
        # Consume A's first token: A is prefillled and actively decoding
        # when B and C join.
        itA = iter(sA)
        next(itA)
        sB = server.submit(pB, 8)
        sC = server.submit(pC, 8, temperature=0.7, rng=42)
        outA = sA.result(timeout=120)
        outB = sB.result(timeout=120)
        outC = sC.result(timeout=120)
        snap = server.metrics.snapshot()

    np.testing.assert_array_equal(outA, refA)
    np.testing.assert_array_equal(outB, refB)
    np.testing.assert_array_equal(outC, refC)
    # Continuous batching actually happened: the engine held more than
    # one active slot at some decode step.
    assert snap["max_active_slots"] >= 2


def test_streaming_iterator_yields_generates_tokens(model_and_vars):
    model, variables = model_and_vars
    p = _prompt(3, 4)
    ref = np.asarray(generate(model, variables, p[None], 6))[0]
    with Server(model, variables, max_batch=2) as server:
        toks = list(server.submit(p, 6))
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref[4:])


def test_eos_frees_slot_and_truncates(model_and_vars):
    """A request that hits EOS stops there (its output is generate()'s,
    cut after the EOS token) and its slot returns to the pool."""
    model, variables = model_and_vars
    # EOS := a generated token whose FIRST occurrence is past token 0,
    # so the request demonstrably decodes a few tokens before stopping.
    # Greedy decode from a random init can collapse to one repeated
    # token, so scan prompt seeds for one that yields a usable EOS.
    for seed in range(4, 64):
        p = _prompt(seed, 6)
        ref = np.asarray(generate(model, variables, p[None], 12))[0]
        gen = ref[6:]
        k = next(
            (i for i in range(1, 12) if gen[i] not in gen[:i]), None
        )
        if k is not None:
            break
    else:
        pytest.skip("no prompt produced a distinct mid-stream token")
    eos = int(gen[k])
    with Server(model, variables, max_batch=2) as server:
        out = server.complete(p, 12, eos_token_id=eos, timeout=120)
        # Slot recycled: engine drains and the slot returns to the pool
        # (poll — the loop thread releases just after the step returns).
        deadline = time.monotonic() + 10
        while (server.scheduler.free_slot_count() < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert server.engine.free_capacity() == 2
        assert server.scheduler.free_slot_count() == 2
    np.testing.assert_array_equal(out, ref[: 6 + k + 1])
    assert out[-1] == eos


def test_backpressure_rejects_past_watermark(model_and_vars):
    model, variables = model_and_vars
    with Server(model, variables, max_batch=1, max_queue=2) as server:
        # One long request occupies the only slot...
        first = server.submit(_prompt(5, 4), 48)
        iter_first = iter(first)
        next(iter_first)  # it is actively decoding
        # ...two more fill the queue; the fourth must be rejected.
        q1 = server.submit(_prompt(6, 4), 4)
        q2 = server.submit(_prompt(7, 4), 4)
        with pytest.raises(AdmissionError, match="watermark"):
            server.submit(_prompt(8, 4), 4)
        assert server.metrics.snapshot()["requests_rejected"] == 1
        for s in (first, q1, q2):
            s.result(timeout=120)


def test_deadline_expires_queued_request(model_and_vars):
    model, variables = model_and_vars
    with Server(model, variables, max_batch=1, max_queue=4) as server:
        blocker = server.submit(_prompt(9, 4), 48)
        next(iter(blocker))
        # Deadline far shorter than the blocker's remaining decode.
        doomed = server.submit(_prompt(10, 4), 4, deadline=1e-3)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=120)
        blocker.result(timeout=120)


def test_metrics_populated(model_and_vars):
    model, variables = model_and_vars
    with Server(model, variables, max_batch=2) as server:
        server.complete(_prompt(11, 5), 8, timeout=120)
        server.complete(_prompt(12, 3), 8, timeout=120)
        snap = server.metrics.log()
    assert snap["requests_completed"] == 2
    assert snap["ttft_p50_ms"] > 0
    assert snap["tokens_per_sec_busy"] > 0
    assert snap["decode_steps_total"] >= 7  # 2 requests x 7 decode steps
    assert snap["tokens_total"] == 16
    assert 0 < snap["slot_occupancy_mean"] <= 1


def test_submit_validates_requests(model_and_vars):
    model, variables = model_and_vars
    with Server(model, variables, max_batch=1) as server:
        with pytest.raises(ValueError, match="non-empty"):
            server.submit(np.asarray([], np.int32), 4)
        with pytest.raises(ValueError, match="max_len"):
            server.submit(_prompt(13, 8), 1000)
        with pytest.raises(ValueError, match="max_new_tokens"):
            server.submit(_prompt(13, 8), 0)
        with pytest.raises(ValueError, match="eos_token_id"):
            server.submit(_prompt(13, 8), 4, eos_token_id=50_000)


def test_prefill_bucketing_compiles_once_per_bucket(model_and_vars):
    """Prompt lengths sharing a power-of-two bucket share one compiled
    prefill program (the compile cache holds one entry per bucket)."""
    from ml_trainer_tpu.generate import _COMPILED

    model, variables = model_and_vars
    with Server(model, variables, max_batch=2) as server:
        for n in (5, 6, 7, 8):  # all in the 8-bucket
            server.complete(_prompt(n, n), 2, timeout=120)
    buckets = [
        k[2] for k in _COMPILED._data if k[0] == "serve_prefill"
        and k[1] == model
    ]
    assert buckets.count(8) == 1


def test_http_front_end_round_trip(model_and_vars):
    import json
    import urllib.request

    model, variables = model_and_vars
    p = _prompt(14, 4)
    ref = np.asarray(generate(model, variables, p[None], 6))[0]
    with Server(model, variables, max_batch=2) as server:
        host, port = server.serve_http(port=0)
        base = f"http://{host}:{port}"
        body = json.dumps(
            {"prompt": [int(t) for t in p], "max_new_tokens": 6}
        ).encode()
        req = urllib.request.Request(
            f"{base}/v1/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        with urllib.request.urlopen(
            f"{base}/metrics.json", timeout=30
        ) as resp:
            snap = json.loads(resp.read())
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            prom = resp.read().decode()
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
            assert health["ok"] is True and health["healthy"] is True
    np.testing.assert_array_equal(np.asarray(out["tokens"], np.int32), ref)
    assert snap["requests_completed"] >= 1
    # /metrics is Prometheus text exposition now (the telemetry spine);
    # the resilience counters must be scrapeable.
    assert "# TYPE serving_requests_completed gauge" in prom
    assert "serving_watchdog_trips 0" in prom
    for line in prom.splitlines():
        assert line.startswith("#") or " " in line, line


def test_health_payload_golden_shape(model_and_vars):
    """The /healthz payload the router places requests on: the field
    set (and the placement-critical types) is a compatibility surface —
    role, queue_depth, kv_pages_free and active_slots must exist with
    live values on both paged and contiguous servers."""
    model, variables = model_and_vars
    with Server(model, variables, max_batch=2, kv_page_size=8,
                role="decode") as server:
        server.complete(_prompt(30, 5), 4, timeout=120)
        payload = server.health()
    assert sorted(payload) == [
        "active_requests", "active_slots", "adapters_resident",
        "adoptions_pending", "closed", "compile_events_post_warmup_total",
        "degradation_level", "draining", "healthy", "kv_pages_free",
        "kv_pages_total", "max_slots", "mono_epoch", "ok", "pid",
        "queue_depth", "queued_requests", "reason", "role",
        "trace_now_us", "transport", "uptime_s", "weights_fp",
    ]
    assert payload["ok"] is True and payload["role"] == "decode"
    # Deploys key KV portability on this: same-process servers sharing
    # variables must report the same fingerprint.
    assert payload["weights_fp"].startswith("w:")
    # Process-identity fields (serving/fleet.py routes on these to tell
    # a worker process from an in-process replica).
    assert payload["pid"] == os.getpid()
    assert payload["transport"] == "inproc"
    assert payload["uptime_s"] >= 0
    assert payload["active_slots"] == 0 and payload["queue_depth"] == 0
    assert payload["max_slots"] == 2
    # Paged server: the pool gauges are live numbers the router ranks on.
    assert payload["kv_pages_total"] == 2 * (64 // 8)
    assert 0 < payload["kv_pages_free"] <= payload["kv_pages_total"]
    # No adapter pool on this server: the field exists (the router reads
    # it unconditionally) but is None, like kv_pages_free on contiguous.
    assert payload["adapters_resident"] is None
    with Server(model, variables, max_batch=1) as contig:
        p2 = contig.health()
    assert p2["role"] == "both" and p2["kv_pages_free"] is None


def test_close_fails_inflight_requests_instead_of_hanging(model_and_vars):
    """close() with work still queued/active must fail those streams
    loudly — a blocked result() after shutdown would hang forever."""
    model, variables = model_and_vars
    server = Server(model, variables, max_batch=1, max_queue=4)
    active = server.submit(_prompt(15, 4), 48)
    next(iter(active))  # occupying the only slot
    queued = server.submit(_prompt(16, 4), 4)
    server.close()
    for s in (active, queued):
        with pytest.raises(RuntimeError, match="server closed"):
            s.result(timeout=30)


def test_lru_bounds_compiled_programs():
    from ml_trainer_tpu.utils.utils import LRUCache

    lru = LRUCache(maxsize=3)
    for i in range(5):
        lru[i] = i * 10
    assert len(lru) == 3
    assert lru.get(0) is None and lru.get(1) is None
    assert lru.get(4) == 40
    # get() refreshes recency: 2 survives the next insert, 3 does not.
    assert lru.get(2) == 20
    lru[5] = 50
    assert lru.get(3) is None and lru.get(2) == 20


def test_metrics_snapshot_hammer_under_concurrent_recording():
    """The crash-fix hunt for ServingMetrics.snapshot(): every record_*
    path hammered from threads while snapshot()/log()/publish() scrape
    concurrently.  Pins the concurrency contract — no ZeroDivisionError
    on empty windows (fresh instance, spec hist empty, zero busy time),
    no mutated-during-iteration crashes, and values stay finite."""
    import threading

    from ml_trainer_tpu.serving.metrics import ServingMetrics
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry

    m = ServingMetrics(window=8)  # tiny window: rollover under fire
    stop = threading.Event()
    errors = []

    def recorder(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                m.record_ttft(float(rng.random()))
                m.record_prefill(float(rng.random()) * 1e-3)
                m.record_step(float(rng.random()) * 1e-3,
                              int(rng.integers(0, 5)), 4, 1)
                m.record_admission(int(rng.integers(0, 9)))
                m.record_completion()
                m.record_spec([int(a) for a in rng.integers(0, 4, 3)], 3)
                m.record_queue_depth(int(rng.integers(0, 9)))
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    def scraper():
        reg = MetricsRegistry()
        try:
            while not stop.is_set():
                snap = m.snapshot()
                assert snap["slot_occupancy_mean"] <= 1.0
                m.publish(reg)
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    # An EMPTY metrics object must snapshot cleanly too (every divisor
    # has a zero-denominator guard).
    empty = ServingMetrics().snapshot()
    assert empty["tokens_per_sec_busy"] == 0.0
    assert empty["spec_acceptance_rate"] == 0.0
    assert empty["spec_tokens_per_step"] == 0.0
    with pytest.raises(ValueError, match="window"):
        ServingMetrics(window=0)

    threads = [threading.Thread(target=recorder, args=(i,))
               for i in range(3)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    final = m.snapshot()
    assert final["requests_completed"] > 0
    assert final["spec_acceptance_rate"] <= 1.0
