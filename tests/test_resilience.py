"""Resilience layer chaos matrix (ml_trainer_tpu/resilience/).

Every fault class in ``FaultPlan`` is injected deterministically and the
corresponding defense verified end to end on CPU:

* ``nan_grad``      -> on-device guard skips the step (no recompile),
                       counters land in history, run stays finite;
* ``preempt``       -> clean exit + emergency checkpoint, and the
                       resumed trajectory is BIT-IDENTICAL to an
                       uninterrupted run (mid-epoch, not just per-epoch);
* ``ckpt_truncate`` -> CRC catches it, the corrupt dir is quarantined,
                       restore falls back to the newest valid checkpoint;
* ``decode_wedge``  -> the serving watchdog fails all in-flight clients
                       with a structured error and reports unhealthy —
                       nobody hangs;
* ``decode_error``  -> the NativeLoader surfaces injected corrupt-sample
                       accounting loudly.

The fast subset runs in tier-1; the heavier combined scenarios carry
``@pytest.mark.slow``.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel
from ml_trainer_tpu import checkpoint as ckpt
from ml_trainer_tpu.checkpoint.checkpoint import CheckpointCorrupt
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.resilience import FaultPlan, faults
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def make_trainer(model_dir, epochs=2, size=64, **kw):
    t = custom_pre_process_function()  # float batches: NaN-poisonable
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=size, seed=0, transform=t),
                  SyntheticCIFAR10(size=32, seed=1, transform=t)),
        epochs=epochs, batch_size=16, model_dir=str(model_dir),
        metric=None, lr=0.01, **kw,
    )


def params_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# --------------------------------------------------------------- fault plans
def test_fault_plan_parse_roundtrip():
    plan = FaultPlan.parse(
        "nan_grad@step=12;ckpt_truncate@epoch=1;preempt@step=40;"
        "decode_wedge@step=5,secs=2"
    )
    kinds = [f.kind for f in plan.faults]
    assert kinds == ["nan_grad", "ckpt_truncate", "preempt", "decode_wedge"]
    assert plan.faults[0].step == 12
    assert plan.faults[1].epoch == 1
    assert plan.faults[3].secs == 2.0
    # fire() consumes exactly one firing, only on a matching trigger.
    assert plan.fire("nan_grad", step=11) is None
    assert plan.fire("nan_grad", step=12) is not None
    assert plan.fire("nan_grad", step=12) is None
    assert plan.fire("ckpt_truncate", epoch=2) is None
    assert plan.fire("ckpt_truncate", epoch=1) is not None
    assert len(plan.remaining()) == 2


def test_fault_plan_count_window_and_env(monkeypatch):
    plan = FaultPlan.parse("nan_grad@step=5,count=3")
    assert plan.fire("nan_grad", step=4) is None
    for s in (5, 6, 7):
        assert plan.fire("nan_grad", step=s) is not None
    assert plan.fire("nan_grad", step=8) is None
    # Env-var plumbing: active_plan() parses and caches per value.
    monkeypatch.setenv(faults.ENV_VAR, "preempt@step=2")
    p = faults.active_plan()
    assert p is not None and p.faults[0].kind == "preempt"
    assert faults.active_plan() is p  # cached
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.active_plan() is None


def test_fault_plan_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("meteor_strike@step=1")
    with pytest.raises(ValueError, match="unknown fault key"):
        FaultPlan.parse("nan_grad@banana=1")
    with pytest.raises(ValueError, match="malformed"):
        FaultPlan.parse("nan_grad@step")


# ------------------------------------------------------------ nan_grad guard
def test_nan_grad_step_skipped_and_counted(tmp_path):
    with faults.injected("nan_grad@step=3"):
        t = make_trainer(tmp_path, epochs=2)
        t.fit()
    assert t.history["skipped_steps"] == [1, 0]
    assert all(np.isfinite(v) for v in t.history["train_loss"])
    assert all(
        np.all(np.isfinite(leaf)) for leaf in jax.tree.leaves(t.state.params)
    )
    assert int(jax.device_get(t.state.skipped_steps)) == 1


def test_guard_off_vs_on_identical_trajectory(tmp_path):
    """With all-finite math the guard's where-selects are exact no-ops:
    guarded and unguarded runs produce bit-identical params."""
    a = make_trainer(tmp_path / "a", epochs=1)
    a.fit()
    b = make_trainer(tmp_path / "b", epochs=1, nonfinite_guard=False)
    b.fit()
    assert a.train_losses == b.train_losses
    assert params_equal(a.state.params, b.state.params)


def test_rollback_after_consecutive_bad_steps(tmp_path):
    """K consecutive non-finite steps trigger restore-from-last-good plus
    LR backoff (checked at the log_every sync cadence)."""
    with faults.injected("nan_grad@step=5,count=3"):
        t = make_trainer(
            tmp_path, epochs=2, save_every_steps=1, rollback_bad_steps=2,
        )
        t.log_every = 1  # check the streak at every step
        t.fit()
    assert t._lr_scale == pytest.approx(0.5)  # one rollback, one backoff
    assert sum(t.history["skipped_steps"]) >= 2
    assert all(np.isfinite(v) for v in t.history["train_loss"])


# ----------------------------------------------------------- preempt/resume
def test_preempt_resume_bit_exact_mid_epoch(tmp_path):
    """THE acceptance scenario: preemption mid-epoch-2, then resume —
    history and final params bit-identical to the uninterrupted run."""
    ref = make_trainer(tmp_path / "ref", epochs=2)
    ref.fit()

    d = tmp_path / "pre"
    with faults.injected("preempt@step=6"):  # batch 2 of epoch 2 (4/epoch)
        t1 = make_trainer(d, epochs=2, save_every_steps=2)
        t1.fit()
    assert t1.preempted
    assert len(t1.train_losses) == 1  # the partial epoch recorded nothing
    marker = os.path.join(str(d), "checkpoints", "PREEMPTED.json")
    assert os.path.exists(marker)
    assert json.load(open(marker))["epoch"] == 2

    t2 = make_trainer(d, epochs=2, save_every_steps=2)
    t2.fit(resume=True)
    assert not os.path.exists(marker)  # consumed on resume
    assert t2.history["epochs"] == ref.history["epochs"]
    assert t2.history["train_loss"] == ref.history["train_loss"]
    assert t2.history["val_loss"] == ref.history["val_loss"]
    assert params_equal(ref.state.params, t2.state.params)


def test_sigterm_requests_clean_preemption(tmp_path):
    """A real SIGTERM takes the same path as the injected fault: finish
    the step, emergency-checkpoint, exit fit() with preempted=True."""
    import signal

    t = make_trainer(tmp_path, epochs=50, size=256, save_every_steps=4)
    timer = threading.Timer(
        1.5, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    timer.start()
    try:
        t.fit()
    finally:
        timer.cancel()
    assert t.preempted
    assert os.path.exists(
        os.path.join(str(tmp_path), "checkpoints", "PREEMPTED.json")
    )
    # Handlers restored after fit (or pytest's SIGTERM handling breaks).
    assert signal.getsignal(signal.SIGTERM) != t._on_preempt_signal


def test_save_every_steps_requires_per_batch_dispatch(tmp_path):
    with pytest.raises(ValueError, match="steps_per_execution"):
        make_trainer(tmp_path, save_every_steps=2, steps_per_execution=3)


# ------------------------------------------------------- checkpoint integrity
def make_ckpt_state(seed=0):
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops import get_optimizer
    from ml_trainer_tpu.train_state import TrainState
    import jax.numpy as jnp

    model = get_model("gpt2_tiny")
    variables = model.init(
        {"params": jax.random.PRNGKey(seed)}, jnp.ones((1, 16), jnp.int32),
        train=False,
    )
    tx = get_optimizer("adamw", 1e-3)
    params = variables["params"]
    return TrainState(
        step=jnp.asarray(7, jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={},
        rng=jax.random.PRNGKey(1),
    )


def test_ckpt_truncate_quarantined_and_fallback(tmp_path):
    """The injected truncation passes the commit rename but fails CRC:
    latest_valid_checkpoint quarantines it and falls back."""
    state = make_ckpt_state()
    good = ckpt.save_checkpoint(str(tmp_path), state, {"train_loss": [1.0]},
                                epoch=1)
    with faults.injected("ckpt_truncate@epoch=2"):
        bad = ckpt.save_checkpoint(
            str(tmp_path), state, {"train_loss": [1.0, 0.5]}, epoch=2
        )
    # The corrupt checkpoint is committed (manifest present) but invalid.
    assert os.path.exists(os.path.join(bad, "manifest.json"))
    with pytest.raises(CheckpointCorrupt, match="CRC32"):
        ckpt.verify_checkpoint(bad)
    assert ckpt.latest_checkpoint(str(tmp_path)) == bad  # naive scan bites
    assert ckpt.latest_valid_checkpoint(str(tmp_path)) == good
    assert os.path.isdir(bad + ".corrupt")  # quarantined out of the scan
    assert not os.path.exists(bad)
    restored, hist, epoch = ckpt.restore_checkpoint(
        good, ckpt.fetch_to_host(make_ckpt_state(seed=9))
    )
    assert epoch == 1 and hist["train_loss"] == [1.0]
    assert params_equal(state.params, restored.params)


def test_restore_raises_on_crc_mismatch(tmp_path):
    state = make_ckpt_state()
    path = ckpt.save_checkpoint(str(tmp_path), state, {}, epoch=1)
    leaves = [f for f in os.listdir(path) if f.endswith(".npy")]
    victim = os.path.join(path, sorted(leaves)[-1])
    with open(victim, "r+b") as fp:
        fp.truncate(os.path.getsize(victim) // 2)
    with pytest.raises(CheckpointCorrupt, match="CRC32"):
        ckpt.restore_checkpoint(
            path, ckpt.fetch_to_host(make_ckpt_state(seed=3))
        )


def test_trainer_resume_falls_back_past_corrupt_checkpoint(tmp_path):
    """fit(resume=True) with a corrupt newest checkpoint quarantines it
    and resumes from the previous epoch instead of crashing."""
    t1 = make_trainer(tmp_path, epochs=2)
    t1.fit()
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    newest = ckpt.latest_checkpoint(ckpt_dir)
    assert newest.endswith("checkpoint_2")
    leaves = [f for f in os.listdir(newest) if f.endswith(".npy")]
    with open(os.path.join(newest, sorted(leaves)[-1]), "r+b") as fp:
        fp.truncate(1)
    t2 = make_trainer(tmp_path, epochs=3)
    t2.fit(resume=True)
    assert os.path.isdir(newest + ".corrupt")
    # Fell back to epoch 1's checkpoint: epochs 2 and 3 re-trained.
    assert t2.history["epochs"] == [1, 2, 3]
    assert all(np.isfinite(v) for v in t2.history["train_loss"])


def test_prune_never_deletes_newest_committed_with_inflight_write(tmp_path):
    """Regression (satellite): an uncommitted mid-flight directory (v3
    writes shard files before the commit manifest) must not count toward
    ``keep`` — with keep=1 the newest COMMITTED checkpoint survives."""
    state = make_ckpt_state()
    for e in (1, 2, 3):
        ckpt.save_checkpoint(str(tmp_path), state, {}, epoch=e, keep=0)
    # Simulate a newer write mid-flight: committed manifest not yet there.
    inflight = os.path.join(str(tmp_path), "checkpoint_4")
    os.makedirs(inflight)
    with open(os.path.join(inflight, "leaf_00000_s0_p00000.npy"), "wb") as f:
        f.write(b"\x93NUMPY partial")
    ckpt.prune_checkpoints(str(tmp_path), keep=1)
    assert not os.path.exists(os.path.join(str(tmp_path), "checkpoint_1"))
    assert not os.path.exists(os.path.join(str(tmp_path), "checkpoint_2"))
    # Newest committed survives; the in-flight dir is untouched debris.
    assert os.path.exists(os.path.join(str(tmp_path), "checkpoint_3"))
    assert os.path.exists(inflight)
    assert ckpt.latest_checkpoint(str(tmp_path)).endswith("checkpoint_3")


# ------------------------------------------------------------- native loader
def test_native_loader_decode_error_fault(tmp_path):
    from ml_trainer_tpu.data.native import NativeLoader, native_available

    if not native_available():
        pytest.skip("native batch worker unavailable (no g++)")
    ds = SyntheticCIFAR10(size=32, seed=0)
    loader = NativeLoader(ds, batch_size=16, shuffle=False, seed=0)
    with faults.injected("decode_error@epoch=0"):
        with pytest.raises(RuntimeError, match="failed JPEG decode"):
            list(loader)
    loader.set_epoch(1)  # next epoch: fault consumed, loader healthy
    assert len(list(loader)) == 2
    loader.stop()


# ------------------------------------------------------------------- serving
@pytest.fixture(scope="module")
def served_model():
    from ml_trainer_tpu.models import get_model

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


def test_decode_wedge_watchdog_fails_clients_fast(served_model):
    """A wedged decode step must fail every waiting client with a
    structured error (never hang), mark the server unhealthy, and refuse
    new admissions."""
    from ml_trainer_tpu.serving import EngineUnhealthy, Server

    model, variables = served_model
    # Warm the compiled programs (process-global LRU) through a throwaway
    # watchdog-less server: first-hit compiles run on the engine loop
    # thread and would trip a 1s watchdog as a false positive.
    with Server(model, variables, max_batch=2, watchdog_timeout=None) as w:
        w.complete(_prompt(0, 5), 2, timeout=120)
    with faults.injected("decode_wedge@step=8,secs=120") as plan:
        srv = Server(model, variables, max_batch=2, watchdog_timeout=1.0)
        try:
            s = srv.submit(_prompt(1, 5), 32)
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="wedged"):
                s.result(timeout=60)
            assert time.monotonic() - t0 < 30  # failed fast, not hung
            health = srv.health()
            assert not health["ok"] and "wedged" in health["reason"]
            with pytest.raises(EngineUnhealthy, match="wedged"):
                srv.submit(_prompt(2, 4), 4)
            assert srv.metrics.snapshot()["watchdog_trips"] == 1
        finally:
            plan.release_wedge()
            srv.close()


def test_engine_thread_death_propagates_to_streams(served_model):
    """Satellite: if the engine thread dies, every waiting result()/
    iterator gets the exception instead of blocking forever."""
    from ml_trainer_tpu.serving import EngineUnhealthy, Server

    model, variables = served_model
    srv = Server(model, variables, max_batch=2, watchdog_timeout=None)
    try:
        srv.complete(_prompt(3, 4), 2, timeout=120)  # warm

        class Boom(BaseException):  # dodges the loop's except Exception
            pass

        def die(*a, **kw):
            raise Boom("engine exploded")

        srv.engine.step = die
        s = srv.submit(_prompt(4, 4), 8)
        with pytest.raises(RuntimeError, match="engine thread died"):
            s.result(timeout=60)
        with pytest.raises(EngineUnhealthy):
            srv.submit(_prompt(5, 4), 4)
        assert not srv.health()["healthy"]
    finally:
        srv.close()


def test_result_timeout_honored_when_engine_dead(served_model):
    """Satellite: blocking result() honors its timeout even when the
    engine is silently stuck (watchdog disabled here on purpose)."""
    from ml_trainer_tpu.serving import Server

    model, variables = served_model
    srv = Server(model, variables, max_batch=2, watchdog_timeout=None)
    release = threading.Event()
    try:
        srv.complete(_prompt(6, 4), 2, timeout=120)  # warm

        def stuck(*a, **kw):
            release.wait(60)
            return []

        srv.engine.step = stuck
        s = srv.submit(_prompt(7, 4), 8)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="not finished within"):
            s.result(timeout=0.5)
        assert time.monotonic() - t0 < 5
    finally:
        release.set()
        srv.close()


def test_drain_stops_admission_and_finishes_inflight(served_model):
    from ml_trainer_tpu.serving import AdmissionError, Server

    model, variables = served_model
    srv = Server(model, variables, max_batch=2)
    try:
        srv.complete(_prompt(8, 4), 2, timeout=120)  # warm
        streams = [srv.submit(_prompt(9 + i, 4), 6) for i in range(3)]
        assert srv.drain(timeout=120)
        with pytest.raises(AdmissionError, match="draining"):
            srv.submit(_prompt(12, 4), 4)
        for s in streams:  # drained means FINISHED, not dropped
            assert len(s.result(timeout=10)) == 10
        health = srv.health()
        assert health["draining"] and health["healthy"] and not health["ok"]
    finally:
        srv.close()


def test_healthz_reports_unhealthy_with_503(served_model):
    """The HTTP surface of the watchdog: /healthz flips to 503 with the
    wedge reason once the watchdog trips."""
    import urllib.error
    import urllib.request

    from ml_trainer_tpu.serving import Server

    model, variables = served_model
    with Server(model, variables, max_batch=2, watchdog_timeout=None) as w:
        w.complete(_prompt(20, 5), 2, timeout=120)  # warm (see wedge test)
    with faults.injected("decode_wedge@step=6,secs=120") as plan:
        srv = Server(model, variables, max_batch=2, watchdog_timeout=1.0)
        try:
            host, port = srv.serve_http(port=0)
            base = f"http://{host}:{port}"
            with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
                assert json.loads(r.read())["ok"] is True
            s = srv.submit(_prompt(21, 5), 32)
            with pytest.raises(RuntimeError):
                s.result(timeout=60)
            try:
                urllib.request.urlopen(f"{base}/healthz", timeout=30)
                raise AssertionError("healthz should be 503 when wedged")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                payload = json.loads(e.read())
                assert payload["healthy"] is False
                assert "wedged" in payload["reason"]
        finally:
            plan.release_wedge()
            srv.close()


# ------------------------------------------------------------- slow matrix
@pytest.mark.slow
def test_chaos_matrix_combined_run(tmp_path):
    """The full storm in one training run: NaN steps, preemption and a
    corrupted checkpoint across epochs — the run still converges to the
    uninterrupted trajectory's epoch count with finite history."""
    ref = make_trainer(tmp_path / "ref", epochs=3, size=128)
    ref.fit()

    d = tmp_path / "storm"
    # Epoch 1 (8 steps/epoch): one NaN step.  Epoch 2: preempted at
    # step 12 (batch 4).  The epoch-1 checkpoint gets truncated AFTER
    # resume consumed the emergency checkpoint (quarantine fallback is
    # separately covered; here it proves CRC tolerates live traffic).
    with faults.injected("nan_grad@step=3;preempt@step=12"):
        t1 = make_trainer(d, epochs=3, size=128, save_every_steps=2)
        t1.fit()
    assert t1.preempted and t1.history["skipped_steps"] == [1]
    t2 = make_trainer(d, epochs=3, size=128, save_every_steps=2)
    t2.fit(resume=True)
    assert t2.history["epochs"] == [1, 2, 3]
    assert t2.history["skipped_steps"] == [1, 0, 0]
    assert all(np.isfinite(v) for v in t2.history["train_loss"])
    # The NaN-skipped epoch diverges from ref by the skipped update, but
    # epochs all completed and the state is healthy/finite.
    assert all(
        np.all(np.isfinite(leaf))
        for leaf in jax.tree.leaves(t2.state.params)
    )


@pytest.mark.slow
def test_preempt_resume_bit_exact_with_metric_and_ema(tmp_path):
    """Bit-exact mid-epoch resume composes with EMA weights and a metric
    (both live in the checkpointed state/accumulators)."""
    def mk(p, **kw):
        tr = custom_pre_process_function()
        return Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0, transform=tr),
                      SyntheticCIFAR10(size=32, seed=1, transform=tr)),
            epochs=2, batch_size=16, model_dir=str(p), metric="accuracy",
            lr=0.01, ema_decay=0.9, **kw,
        )

    ref = mk(tmp_path / "ref")
    ref.fit()
    d = tmp_path / "pre"
    with faults.injected("preempt@step=7"):
        mk(d, save_every_steps=1).fit()
    t2 = mk(d, save_every_steps=1)
    t2.fit(resume=True)
    assert t2.history["train_loss"] == ref.history["train_loss"]
    assert t2.history["train_metric"] == ref.history["train_metric"]
    assert params_equal(ref.state.params, t2.state.params)
    assert params_equal(ref.state.ema_params, t2.state.ema_params)


# ------------------------------------------------------------------ elastic
# The in-flight drain->reshape->continue controller and the topology-
# flexible restore machinery behind it (resilience/elastic.py): the
# 8-virtual-device suite mesh decomposes into simulated hosts, a
# host_kill fault drops one, and the SAME fit() call finishes with the
# uninterrupted run's trajectory (the 'global' batch policy changes
# placement, not math).

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from ml_trainer_tpu.parallel import create_mesh  # noqa: E402
from ml_trainer_tpu.resilience import elastic  # noqa: E402
from ml_trainer_tpu.resilience.elastic import (  # noqa: E402
    ElasticConfig,
    ReshardError,
    TopologyError,
)


def make_elastic_trainer(model_dir, epochs=2, **kw):
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=64, seed=0),
                  SyntheticCIFAR10(size=32, seed=1)),
        epochs=epochs, batch_size=16, model_dir=str(model_dir),
        metric=None, lr=0.01, mesh_shape={"data": 8}, **kw,
    )


def test_host_fault_parse_and_spec():
    plan = FaultPlan.parse(
        "host_kill@step=9,host=1;host_hang@step=3,host=0,secs=1.5"
    )
    kill, hang = plan.faults
    assert (kill.kind, kill.step, kill.host) == ("host_kill", 9, 1)
    assert (hang.kind, hang.host, hang.secs) == ("host_hang", 0, 1.5)
    assert "host=1" in kill.spec()
    with pytest.raises(ValueError, match="host"):
        FaultPlan.parse("nan_grad@step=2,host=1")


def test_elastic_reshape_continues_same_fit(tmp_path):
    """Kill 1 of 2 simulated hosts mid-epoch: the same fit() call
    drains, reshapes 8 -> 4 devices, and finishes with the
    uninterrupted trajectory (preserve-global policy: placement
    changed, math did not)."""
    ref = make_elastic_trainer(tmp_path / "ref")
    ref.fit()
    with faults.injected("host_kill@step=3,host=1"):
        t = make_elastic_trainer(tmp_path / "chaos", elastic=2)
        t.fit()
    assert not t.preempted
    assert int(t.mesh.size) == 4 and t._live_hosts == [0]
    assert len(t.history["reshapes"]) == 1
    rec = t.history["reshapes"][0]
    assert rec["trigger"] == "host_kill" and rec["lost_host"] == 1
    assert rec["old_topology"] == {"data": 8}
    assert rec["new_topology"] == {"data": 4}
    assert rec["steps_lost"] == 0 and rec["global_batch"] == 16
    # Trajectory: device count changes the reduction tree, not the math.
    assert t.train_losses == pytest.approx(ref.train_losses, rel=2e-4)
    # Forensics: the flight ring carries the reshape beside the steps.
    kinds = [r["kind"] for r in t._flight.records()]
    assert "reshape" in kinds
    # Downtime was attributed, not folded into compute.
    from ml_trainer_tpu.telemetry import goodput

    assert goodput.snapshot()["reshape"] > 0.0


def test_elastic_per_device_policy_rescales_batch_and_lr(tmp_path):
    """The 'per_device' policy shrinks the global batch by the survivor
    ratio and rescales the LR linearly — both recorded."""
    with faults.injected("host_kill@step=2,host=0"):
        t = make_elastic_trainer(
            tmp_path / "chaos",
            elastic=ElasticConfig(n_hosts=2, batch_policy="per_device"),
        )
        t.fit()
    rec = t.history["reshapes"][0]
    assert rec["old_global_batch"] == 16 and rec["global_batch"] == 8
    assert rec["lr_scale"] == pytest.approx(0.5)
    assert t.global_batch == 8 and t._lr_scale == pytest.approx(0.5)
    assert all(np.isfinite(v) for v in t.train_losses)
    assert len(t.train_losses) == 2


def test_host_kill_without_elastic_degrades_to_preemption(tmp_path):
    with faults.injected("host_kill@step=3,host=1"):
        t = make_elastic_trainer(tmp_path / "k")
        t.fit()
    assert t.preempted
    assert os.path.exists(
        os.path.join(tmp_path / "k", "checkpoints", "PREEMPTED.json")
    )


def test_elastic_validation_errors(tmp_path):
    with pytest.raises(ValueError, match="steps_per_execution"):
        make_elastic_trainer(tmp_path, elastic=2, steps_per_execution=2)
    with pytest.raises(ValueError, match="ambiguous"):
        make_elastic_trainer(tmp_path, elastic=True)
    with pytest.raises(ValueError, match="host groups"):
        # 8-device data axis does not split into 3 equal hosts.
        make_elastic_trainer(tmp_path, elastic=3)
    with pytest.raises(ValueError, match="batch_policy"):
        ElasticConfig(n_hosts=2, batch_policy="nope")
    with pytest.raises(ValueError, match="n_hosts"):
        ElasticConfig(n_hosts=1)


def test_reshard_error_names_axis_and_leaf():
    mesh = create_mesh({"data": 8})
    state = {"w": np.zeros((6, 4), np.float32)}
    shardings = {"w": NamedSharding(mesh, P("data"))}
    with pytest.raises(ReshardError) as ei:
        elastic.validate_reshard(
            state, shardings, source_topology={"axes": {"data": 16}}
        )
    e = ei.value
    assert e.leaf == "w" and e.dim == 0 and e.size == 6
    assert e.axes == ("data",) and e.axis_size == 8
    assert "data" in str(e) and "16" in str(e)  # source vs target named


def test_remap_shardings_zero1_fallback():
    """Carrying shardings onto a smaller mesh re-applies the ZeRO-1
    shape rule: a dim-0 data shard that no longer divides replicates
    instead of erroring (exactly what zero1_opt_shardings would have
    decided on the new mesh)."""
    old = create_mesh({"data": 8})
    new = create_mesh({"data": 6}, devices=jax.devices()[:6])
    state = {
        "divisible": np.zeros((12, 2), np.float32),
        "indivisible": np.zeros((8, 2), np.float32),
        "scalar": np.zeros((), np.float32),
    }
    shardings = {
        "divisible": NamedSharding(old, P("data")),
        "indivisible": NamedSharding(old, P("data")),
        "scalar": NamedSharding(old, P()),
    }
    out = elastic.remap_state_shardings(shardings, state, new)
    assert out["divisible"].spec == P("data")
    assert out["divisible"].mesh is new
    assert out["indivisible"].spec == P()  # 8 % 6 != 0 -> replicated
    elastic.validate_reshard(state, out)  # and the result verifies


def test_precheck_topology_structured_oom():
    with pytest.raises(TopologyError) as ei:
        elastic.precheck_topology(
            MLModel(), (16, 32, 32, 3), mesh_shape={"data": 4},
            capacity_bytes=1024.0,
        )
    v = ei.value.verdict
    assert v["verdict"] == "oom" and v["mesh_shape"] == {"data": 4}
    assert v["peak_bytes"] > v["capacity_bytes"]
    # A sane capacity passes and returns the planner's verdict.
    ok = elastic.precheck_topology(
        MLModel(), (16, 32, 32, 3), mesh_shape={"data": 4}
    )
    assert ok["verdict"] in ("fits", "tight")


def test_checkpoint_manifest_and_marker_record_topology(tmp_path):
    d = tmp_path / "topo"
    with faults.injected("preempt@step=6"):
        t = make_elastic_trainer(d, save_every_steps=2)
        t.fit()
    assert t.preempted
    latest = ckpt.latest_valid_checkpoint(str(d / "checkpoints"))
    topo = ckpt.checkpoint_topology(latest)
    assert topo is not None
    assert topo["axes"] == {"data": 8} and topo["device_count"] == 8
    marker = json.load(open(d / "checkpoints" / "PREEMPTED.json"))
    assert marker["mesh"]["axes"] == {"data": 8}


def test_v3_restore_incompatible_mesh_structured_error(tmp_path):
    """A v3 checkpoint restored onto a mesh a saved shape cannot divide
    fails with a ReshardError naming source vs target axes — not a
    reshape traceback out of make_array_from_callback."""
    mesh = create_mesh({"data": 8})
    state = {
        "ok": jax.device_put(
            np.arange(16, dtype=np.float32), NamedSharding(mesh, P("data"))
        ),
        "bad": jax.device_put(
            np.arange(6, dtype=np.float32), NamedSharding(mesh, P())
        ),
    }
    path = ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=1)
    saved_topo = ckpt.checkpoint_topology(path)
    assert saved_topo["axes"] == {"data": 8}
    target = {
        "ok": NamedSharding(mesh, P("data")),
        "bad": NamedSharding(mesh, P("data")),  # 6 % 8 != 0
    }
    with pytest.raises(ReshardError) as ei:
        ckpt.restore_checkpoint(path, state, target)
    assert ei.value.leaf == "bad" and ei.value.axis_size == 8
    assert ei.value.source_topology["axes"] == {"data": 8}
    # elastic_restore pre-validates the same way (template shapes).
    with pytest.raises(ReshardError):
        elastic.elastic_restore(path, state, target)


def test_goodput_reshape_bucket():
    from ml_trainer_tpu.telemetry import goodput

    assert "reshape" in goodput.BUCKETS
    base = goodput.snapshot()
    goodput.account("reshape", 1.5)
    assert goodput.snapshot()["reshape"] == pytest.approx(
        base["reshape"] + 1.5
    )


def test_straggler_verdict_requests_reshape(tmp_path):
    """The telemetry/cluster.py straggler verdict reaches the elastic
    controller: past the reshape factor it queues a drain+reshape,
    below it it stays an alarm."""
    t = make_elastic_trainer(
        tmp_path,
        elastic=ElasticConfig(n_hosts=2, straggler_reshape_factor=4.0),
    )
    t._on_straggler_verdict(host=1, factor=2.0, step=5)
    assert t._reshape_request is None  # below the reshape factor
    t._on_straggler_verdict(host=1, factor=5.0, step=7)
    assert t._reshape_request is not None
    assert t._reshape_request.trigger == "straggler"
    assert t._reshape_request.lost_host == 1
    t._reshape_request = None

    # And the callback is actually wired through ClusterTelemetry: a
    # fabricated 2-host pod with a 10x host fires the verdict hook.
    calls = []
    from ml_trainer_tpu.telemetry.cluster import ClusterTelemetry

    c = ClusterTelemetry(
        straggler_factor=2.0,
        on_straggler=lambda **kw: calls.append(kw),
    )
    c._ingest(np.asarray([[1.0, 5.0] + [0.0] * 6,
                          [1.0, 50.0] + [0.0] * 6]), step=42)
    assert calls and calls[0]["host"] == 1 and calls[0]["step"] == 42
    assert calls[0]["factor"] == pytest.approx(10.0)
