"""Batched LoRA adapters (ml_trainer_tpu/lora.py, serving/adapter_pool.py).

Ground truths: (1) ``adapter=None`` traffic through a LoRA-enabled
engine is byte-identical to ``generate()`` on the base model — slot 0's
all-zero trash adapter makes the delta an exact float zero; (2) the
frozen base never moves — ``Trainer(lora=...)`` trains only the
``*_lora_A/B`` leaves and the export→hot-load round trip serves the
SAME base bytes; (3) one rank bucket means mixed-rank adapter traffic
and hot-loads mint zero programs after warmup; (4) a prefix-cache hit
under adapter X never serves adapter Y's K/V.
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.generate import _COMPILED, generate
from ml_trainer_tpu.lora import (
    LoraConfig,
    export_lora_artifact,
    load_lora_artifact,
    strip_lora_params,
)
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import (
    AdapterConfig,
    AdapterPool,
    AdapterPoolExhausted,
    Server,
    TenantLoad,
    UnknownAdapter,
    poisson_schedule,
    schedule_from_trace,
    schedule_to_records,
)

PS = 8  # kv page size for the paged legs


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


def _make_artifact(model, path, *, name, rank=4, alpha=8.0,
                   targets=("qkv", "proj"), seed=0, scale=2.0):
    """Fabricate a plausible adapter artifact: init the TRAIN-mode lora
    model (A ~ N(0, 0.01²), B zero) and give B real mass so the adapter
    visibly moves logits."""
    cfg = LoraConfig(rank=rank, alpha=alpha, targets=targets)
    lm = model.clone(lora_rank=rank, lora_alpha=alpha,
                     lora_targets=tuple(targets))
    params = jax.device_get(lm.init(
        {"params": jax.random.PRNGKey(7)}, np.zeros((1, 8), np.int32),
        train=False,
    )["params"])
    key = jax.random.PRNGKey(seed)

    def bump(node):
        out = {}
        for k, v in node.items():
            if hasattr(v, "items"):
                out[k] = bump(v)
            elif "_lora_B" in k:
                nonlocal key
                key, sub = jax.random.split(key)
                out[k] = np.asarray(
                    jax.random.normal(sub, v.shape), np.float32
                ) * scale
            else:
                out[k] = v
        return out

    export_lora_artifact(bump(dict(params)), cfg, path, name=name)
    return path


# ------------------------------------------------ pool mechanics (host)


def test_pool_refcount_eviction_and_exhaustion(model_and_vars, tmp_path):
    model, _ = model_and_vars
    paths = {
        n: _make_artifact(model, str(tmp_path / f"{n}.npz"), name=n,
                          seed=i)
        for i, n in enumerate(("a", "b", "c"))
    }
    pool = AdapterPool(AdapterConfig(
        slots=3, rank=8, targets=("qkv", "proj"),
        sources={n: p for n, p in paths.items()},
    ))
    # 2 loadable slots.  Load a and b; both held.
    slot_a, up_a = pool.acquire("a")
    slot_b, up_b = pool.acquire("b")
    assert up_a is not None and up_b is not None
    assert sorted((slot_a, slot_b)) == [1, 2]
    # Eviction REFUSED while both slots are held: c cannot load.
    with pytest.raises(AdapterPoolExhausted, match="'c'"):
        pool.acquire("c")
    # Residency hit: a second holder of "a" pins the same slot.
    slot_a2, up = pool.acquire("a")
    assert slot_a2 == slot_a and up is None
    assert pool.counters()["hits"] == 1
    # Release a fully; it STAYS resident (warm) until c needs the slot.
    pool.release(slot_a)
    pool.release(slot_a)
    assert pool.resident() == ["a", "b"]
    slot_c, up_c = pool.acquire("c")
    assert slot_c == slot_a and up_c is not None  # LRU victim was a
    assert pool.counters()["evictions"] == 1
    assert pool.resident() == ["b", "c"]
    with pytest.raises(UnknownAdapter, match="'nope'"):
        pool.acquire("nope")
    # Trash slot releases are no-ops; double release of a real pin is
    # refused.
    pool.release(0)
    pool.release(slot_b)
    with pytest.raises(ValueError, match="unheld"):
        pool.release(slot_b)


def test_pool_config_validation(model_and_vars, tmp_path):
    model, _ = model_and_vars
    with pytest.raises(ValueError, match="slots"):
        AdapterConfig(slots=1)
    with pytest.raises(ValueError, match="subset"):
        AdapterConfig(targets=("qkv", "nonsense"))
    # An artifact above the pool's rank bucket is refused at register.
    path = _make_artifact(model, str(tmp_path / "big.npz"), name="big",
                          rank=16)
    pool = AdapterPool(AdapterConfig(slots=3, rank=8))
    with pytest.raises(ValueError, match="rank 16 exceeds"):
        pool.register("big", path)


def test_artifact_round_trip(model_and_vars, tmp_path):
    model, _ = model_and_vars
    path = _make_artifact(model, str(tmp_path / "x.npz"), name="x")
    meta, leaves = load_lora_artifact(path)
    assert meta["rank"] == 4 and meta["n_leaves"] == len(leaves) == 8
    assert all("_lora_" in k for k in leaves)


# --------------------------------------------- serving byte disciplines


def test_adapter_none_bit_identical_and_adapter_changes_logits(
        model_and_vars, tmp_path):
    """The acceptance core: base traffic through a LoRA-enabled server
    (contiguous AND paged) reproduces generate() byte-for-byte, while
    adapter-carrying rows in the SAME decode batch get their own
    deltas."""
    model, variables = model_and_vars
    path = _make_artifact(model, str(tmp_path / "x.npz"), name="x")
    prompts = [_prompt(i, 5 + 3 * i) for i in range(3)]
    refs = [
        np.asarray(generate(model, variables, p[None], 6))[0]
        for p in prompts
    ]
    for paged in (False, True):
        kwargs = {"kv_page_size": PS} if paged else {}
        with Server(model, variables, max_batch=4,
                    adapters=AdapterConfig(
                        slots=4, rank=8, targets=("qkv", "proj"),
                        sources={"x": path},
                    ), **kwargs) as srv:
            # Mixed batch: base + adapter rows decode TOGETHER.
            streams = [srv.submit(p, 6) for p in prompts]
            sx = srv.submit(prompts[0], 6, adapter="x")
            outs = [np.asarray(s.result(timeout=300)) for s in streams]
            out_x = np.asarray(sx.result(timeout=300))
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)
        assert not np.array_equal(out_x, refs[0]), (
            "adapter delta did not reach the logits"
        )


def test_adapter_unknown_and_no_pool_are_structured(model_and_vars):
    model, variables = model_and_vars
    with Server(model, variables, max_batch=2) as srv:
        with pytest.raises(ValueError, match="no adapter pool"):
            srv.submit(_prompt(0, 5), 4, adapter="x")
    with Server(model, variables, max_batch=2,
                adapters=AdapterConfig(slots=3, rank=8)) as srv:
        stream = srv.submit(_prompt(0, 5), 4, adapter="ghost")
        with pytest.raises(RuntimeError, match="unknown adapter 'ghost'"):
            stream.result(timeout=60)


def test_pool_exhaustion_is_structured_error_naming_adapter(
        model_and_vars, tmp_path):
    """Every loadable slot held by an active stream: the next adapter's
    admission fails with a structured error naming it (and the pool
    recovers once a holder finishes)."""
    model, variables = model_and_vars
    pa = _make_artifact(model, str(tmp_path / "a.npz"), name="a", seed=1)
    pb = _make_artifact(model, str(tmp_path / "b.npz"), name="b", seed=2)
    with Server(model, variables, max_batch=3,
                adapters=AdapterConfig(slots=2, rank=8,
                                       sources={"a": pa, "b": pb})) as srv:
        sa = srv.submit(_prompt(0, 5), 40, adapter="a")
        next(iter(sa))          # "a" is resident AND held
        sb = srv.submit(_prompt(1, 5), 4, adapter="b")
        with pytest.raises(RuntimeError,
                           match="adapter pool exhausted loading 'b'"):
            sb.result(timeout=120)
        sa.result(timeout=300)  # the holder finishes -> slot free
        out = np.asarray(
            srv.complete(_prompt(1, 5), 4, adapter="b", timeout=300)
        )
        assert out.size == 9


def test_prefix_cache_isolated_per_adapter(model_and_vars, tmp_path):
    """A cross-adapter probe of a cached prompt gets a MISS: adapter
    K/V differs, so sharing would be wrong logits, not just a side
    channel.  Same-adapter repeats still hit."""
    model, variables = model_and_vars
    path = _make_artifact(model, str(tmp_path / "x.npz"), name="x")
    p = np.concatenate([_prompt(3, 2 * PS), _prompt(4, 3)])
    ref = np.asarray(generate(model, variables, p[None], 4))[0]
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                adapters=AdapterConfig(slots=3, rank=8,
                                       sources={"x": path})) as srv:
        eng = srv.engine
        base1 = np.asarray(srv.complete(p, 4, timeout=300))
        h0, m0 = eng._prefix.hits, eng._prefix.misses
        # Cross-adapter probe of the SAME prompt: a miss, own namespace.
        out_x = np.asarray(srv.complete(p, 4, adapter="x", timeout=300))
        assert (eng._prefix.hits, eng._prefix.misses) == (h0, m0 + 1)
        # Same-adapter repeat: a hit inside the adapter's namespace.
        out_x2 = np.asarray(srv.complete(p, 4, adapter="x", timeout=300))
        assert eng._prefix.hits == h0 + 1
        # Base repeat after the adapter traffic: still hits ITS pages
        # and still reproduces generate() byte-for-byte.
        base2 = np.asarray(srv.complete(p, 4, timeout=300))
    np.testing.assert_array_equal(base1, ref)
    np.testing.assert_array_equal(base2, ref)
    np.testing.assert_array_equal(out_x, out_x2)
    assert not np.array_equal(out_x, base1)


def test_mixed_rank_hot_load_zero_recompiles(model_and_vars, tmp_path):
    """The rank-bucket discipline: after one warmup wave, traffic over
    adapters of DIFFERENT trained ranks plus a mid-run hot-load of a
    brand-new adapter mints zero compiled programs."""
    model, variables = model_and_vars
    r2 = _make_artifact(model, str(tmp_path / "r2.npz"), name="r2",
                        rank=2, seed=1)
    r4 = _make_artifact(model, str(tmp_path / "r4.npz"), name="r4",
                        rank=4, seed=2)
    r8 = _make_artifact(model, str(tmp_path / "r8.npz"), name="r8",
                        rank=8, seed=3)
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                adapters=AdapterConfig(slots=8, rank=8,
                                       sources={"r2": r2, "r4": r4})
                ) as srv:
        p = _prompt(9, 7)
        for a in (None, "r2", "r4"):
            srv.complete(p, 4, adapter=a, timeout=300)
        n_warm = len(_COMPILED._data)
        # Mixed-rank wave + a hot-load under (simulated) traffic.
        srv.complete(_prompt(10, 7), 5, adapter="r2", timeout=300)
        srv.complete(_prompt(11, 7), 5, adapter="r4", timeout=300)
        srv.load_adapter("r8", r8)
        out = np.asarray(
            srv.complete(_prompt(12, 7), 5, adapter="r8", timeout=300)
        )
        n_after = len(_COMPILED._data)
    assert out.size == 12
    assert n_after == n_warm, (
        f"mixed-rank/hot-load traffic compiled {n_after - n_warm} new "
        "program(s)"
    )


def test_eviction_reload_bit_identical(model_and_vars, tmp_path):
    """Evict-then-reload serves the same bytes: the registry keeps the
    host copy, so residency is pure caching."""
    model, variables = model_and_vars
    pa = _make_artifact(model, str(tmp_path / "a.npz"), name="a", seed=1)
    pb = _make_artifact(model, str(tmp_path / "b.npz"), name="b", seed=2)
    p = _prompt(5, 6)
    with Server(model, variables, max_batch=2,
                adapters=AdapterConfig(slots=2, rank=8,
                                       sources={"a": pa, "b": pb})) as srv:
        out_a1 = np.asarray(srv.complete(p, 5, adapter="a", timeout=300))
        # Only ONE loadable slot: b's load evicts idle a.
        srv.complete(p, 5, adapter="b", timeout=300)
        assert srv.engine.adapters.counters()["evictions"] == 1
        out_a2 = np.asarray(srv.complete(p, 5, adapter="a", timeout=300))
    np.testing.assert_array_equal(out_a1, out_a2)


def test_spec_k_with_adapters_refused(model_and_vars):
    model, variables = model_and_vars
    from ml_trainer_tpu.serving import SlotDecodeEngine

    with pytest.raises(ValueError, match="spec_k"):
        SlotDecodeEngine(model, variables, max_batch=2, spec_k=2,
                         adapters=AdapterConfig(slots=3, rank=4))


# ------------------------------------------------- telemetry satellites


def test_adapter_gauges_and_health(model_and_vars, tmp_path):
    model, variables = model_and_vars
    path = _make_artifact(model, str(tmp_path / "x.npz"), name="x")
    from ml_trainer_tpu.telemetry.registry import default_registry

    with Server(model, variables, max_batch=2,
                adapters=AdapterConfig(slots=4, rank=8,
                                       sources={"x": path})) as srv:
        srv.complete(_prompt(0, 5), 4, adapter="x", timeout=300)
        health = srv.health()
        registry = default_registry()
        srv.metrics.publish(registry)
        text = registry.prometheus_text()
        snap = srv.metrics.snapshot()
    assert health["adapters_resident"] == ["x"]
    assert snap["adapter_loads_total"] == 1
    assert snap["adapter_slots_used"] == 1
    assert snap["adapter_pool_bytes"]["used"] > 0
    assert 'serving_adapter_pool_bytes{state="used"}' in text
    assert "serving_adapter_hits_total" in text
    assert "serving_adapter_loads_total 1" in text
    assert "serving_adapter_evictions_total 0" in text


def test_adapter_pool_priced_by_memory_ledger(model_and_vars):
    """The analytic ``adapter_pool_bytes`` formula equals the measured
    device stacks, and the serving ledger carries the component beside
    kv_pool."""
    model, variables = model_and_vars
    from ml_trainer_tpu.serving import SlotDecodeEngine
    from ml_trainer_tpu.telemetry.memory import (
        adapter_pool_bytes,
        gpt2_lora_target_dims,
        serving_kv_ledger,
    )

    targets = ("qkv", "proj", "fc_in", "fc_out")
    eng = SlotDecodeEngine(
        model, variables, max_batch=2, kv_page_size=PS,
        adapters=AdapterConfig(slots=5, rank=4, targets=targets),
    )
    measured = sum(
        int(l.nbytes) for l in jax.tree.leaves(eng._lora_stacks)
    )
    analytic = adapter_pool_bytes(
        5, 4, gpt2_lora_target_dims(model, targets), jnp.float32
    )
    assert analytic == measured
    ledger = serving_kv_ledger(eng)
    comp = ledger.component("adapter_pool")
    assert comp is not None and int(comp.bytes) == measured
    assert ledger.component("kv_pool") is not None


# -------------------------------------------- train -> export -> serve


def test_trainer_lora_round_trip_frozen_base_bit_identity(tmp_path):
    """Trainer(lora=...) freezes the base (bit-identical after fit),
    shrinks optimizer state to the adapter fraction (memory ledger),
    and the exported artifact hot-loads into a server whose base path
    reproduces generate() on the frozen base byte-for-byte."""
    import jax.tree_util as tu

    from ml_trainer_tpu import LoraConfig as TopLoraConfig
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.lora import is_lora_path
    from ml_trainer_tpu.telemetry.memory import train_ledger

    model = get_model("gpt2_tiny", vocab_size=256)
    ds = SyntheticTokens(size=16, seq_len=16, vocab_size=256, seed=0)
    t = Trainer(
        model, datasets=(ds, ds), epochs=2, batch_size=8,
        model_dir=str(tmp_path), metric=None, optimizer="adamw",
        lr=0.05, criterion="cross_entropy",
        lora=TopLoraConfig(rank=4, alpha=8.0, targets=("qkv", "proj")),
    )
    init_params = jax.device_get(t.state.params)
    ledger = train_ledger(t)
    # Frozen leaves carry no moments: opt_state ≪ 2x params (adamw's
    # replicated mu+nu would be ~2x).
    assert ledger.component("opt_state").bytes < (
        0.2 * 2 * ledger.component("params").bytes
    )
    t.fit()
    final_params = jax.device_get(t.state.params)
    n_lora_changed = 0
    finals = {
        tu.keystr(p): v
        for p, v in tu.tree_leaves_with_path(final_params)
    }
    for p, v in tu.tree_leaves_with_path(init_params):
        k = tu.keystr(p)
        if is_lora_path(k):
            n_lora_changed += int(
                not np.array_equal(np.asarray(v), np.asarray(finals[k]))
            )
        else:
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(finals[k]),
                err_msg=f"frozen base leaf changed: {k}",
            )
    assert n_lora_changed >= 4
    path = str(tmp_path / "adapter.npz")
    meta = t.export_lora(path, name="trained")
    assert meta["n_leaves"] == 8

    base_params = strip_lora_params(final_params)
    prompts = [
        np.random.default_rng(i).integers(0, 256, 9).astype(np.int32)
        for i in range(2)
    ]
    base_refs = [
        np.asarray(generate(model, {"params": base_params}, p[None], 4))[0]
        for p in prompts
    ]
    # Train-mode greedy decode of the SAME trained adapter — the
    # served pool path must agree token-for-token.
    lora_refs = [
        np.asarray(
            generate(t.model, {"params": final_params}, p[None], 4)
        )[0]
        for p in prompts
    ]
    with Server(model, {"params": base_params}, max_batch=2,
                adapters=AdapterConfig(slots=3, rank=8,
                                       targets=("qkv", "proj"))) as srv:
        srv.load_adapter("trained", path)
        for p, rb, rl in zip(prompts, base_refs, lora_refs):
            np.testing.assert_array_equal(
                np.asarray(srv.complete(p, 4, timeout=300)), rb,
                err_msg="frozen-base serve path diverged",
            )
            np.testing.assert_array_equal(
                np.asarray(srv.complete(p, 4, adapter="trained",
                                        timeout=300)), rl,
                err_msg="served adapter diverged from train-mode decode",
            )


# --------------------------------------------------- router + loadgen


def test_router_adapter_affinity(model_and_vars, tmp_path):
    """Same (tenant, adapter) traffic consistently lands on ONE prefill
    replica — the residency-affinity property the consistent hash
    exists for."""
    model, variables = model_and_vars
    from ml_trainer_tpu.serving import Router

    path = _make_artifact(model, str(tmp_path / "x.npz"), name="x")
    router = Router.build(
        model, variables, roles=["both", "both"], max_batch=2,
        kv_page_size=PS,
        adapters=AdapterConfig(slots=3, rank=8, sources={"x": path}),
    )
    try:
        p = _prompt(0, 2 * PS)
        for _ in range(4):
            router.complete(p, 4, adapter="x", timeout=300)
        snap = router.snapshot()
        placed = {
            k: v for k, v in snap["requests_total"].items() if v
        }
        assert len(placed) == 1, (
            f"same (tenant, adapter) traffic split across replicas: "
            f"{placed}"
        )
        health = router.health()
        rep = list(health["replicas"].values())[0]
        assert "adapters_resident" in rep
    finally:
        router.close()


def test_loadgen_adapter_mix_rides_recorded_traces():
    load = {
        "pro": TenantLoad(weight=1.0, adapters=("a", "b", None)),
    }
    s1 = poisson_schedule(50.0, 24, 1024, tenants=load, seed=3)
    s2 = poisson_schedule(50.0, 24, 1024, tenants=load, seed=3)
    assert [s.adapter for s in s1] == [s.adapter for s in s2]
    drawn = {s.adapter for s in s1}
    assert {"a", "b", None} <= drawn
    records = schedule_to_records(s1)
    replay = schedule_from_trace(records)
    assert [s.adapter for s in replay] == [s.adapter for s in s1]
    with pytest.raises(ValueError, match="adapters entries"):
        TenantLoad(adapters=("a", ""))
