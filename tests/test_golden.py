"""Golden-run regression check (VERDICT r1 #8).

The reference's committed notebook outputs (01 nb cell-12/16: per-epoch
loss/accuracy + throughput lines) act as its golden-run record.  Ours is
captured by ``GOLDEN_OUT=... python examples/01_local_training.py``
(synthetic CIFAR-10, the zero-egress stand-in): canonically
``tests/golden/local_run_tpu.json`` from the real chip, with
``local_run_cpu.json`` as the stand-in record while the TPU tunnel is
down (the record notes its ``backend``).  This test re-runs the exact
same configuration on the CPU test mesh and asserts the trajectory still
lands where the committed record says, within tolerances generous enough
to absorb CPU-vs-TPU numerics but tight enough to catch real regressions
(broken schedule stepping, loss scaling, seeding, history schema).
"""

import json
import os

import pytest

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow

_GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
# The TPU capture is the canonical record; until a tunnel window produces
# it, the CPU capture (same config/seeds, backend noted inside) keeps the
# regression net ACTIVE rather than skipped.
_CANDIDATES = [
    os.path.join(_GOLDEN_DIR, "local_run_tpu.json"),
    os.path.join(_GOLDEN_DIR, "local_run_cpu.json"),
]
GOLDEN = next((p for p in _CANDIDATES if os.path.exists(p)), _CANDIDATES[0])

HISTORY_KEYS = {
    "epochs", "train_loss", "val_loss", "train_metric", "val_metric",
    "metric_type",
}


@pytest.fixture(scope="module")
def golden():
    if not os.path.exists(GOLDEN):
        pytest.skip("golden record not captured yet")
    with open(GOLDEN) as f:
        return json.load(f)


def test_golden_schema(golden):
    # Records captured after the resilience layer landed also carry the
    # per-epoch skipped_steps counts; both vintages stay valid.
    assert HISTORY_KEYS <= set(golden["history"]) <= (
        HISTORY_KEYS | {"skipped_steps"}
    )
    n = golden["epochs"]
    assert golden["history"]["epochs"] == list(range(1, n + 1))
    for k in ("train_loss", "val_loss", "train_metric", "val_metric"):
        assert len(golden["history"][k]) == n
    assert golden["history"]["metric_type"] == "accuracy"
    assert golden["train_samples_per_sec_incl_compile"] > 0


def test_golden_trajectory_reproduces(golden, tmp_path):
    """Same config, same seeds, CPU mesh — must match the TPU record."""
    from ml_trainer_tpu import MLModel, Loader, Trainer, load_model
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    if not golden.get("synthetic"):
        pytest.skip("golden record was captured on real CIFAR-10, which "
                    "this machine may not have")
    transform = custom_pre_process_function()
    datasets = (
        SyntheticCIFAR10(size=golden["train_size"], transform=transform),
        SyntheticCIFAR10(size=512, transform=transform, seed=1),
    )
    trainer = Trainer(
        MLModel(), datasets=datasets, epochs=golden["epochs"], batch_size=32,
        save_history=True, seed=32, scheduler="CosineAnnealingWarmRestarts",
        optimizer="sgd", momentum=0.9, weight_decay=0.0, lr=0.001,
        criterion="cross_entropy", metric="accuracy", pred_function="softmax",
        model_dir=str(tmp_path),
    )
    trainer.fit()

    h, g = trainer.history, golden["history"]
    # The resilience ledger (skipped_steps from the nonfinite guard,
    # rollbacks from rollback-to-last-good — both added after the golden
    # record was captured) is compared only when the record carries it;
    # a healthy run's counts are all zero either way.
    ledger = {"skipped_steps", "rollbacks"}
    assert set(h) - ledger == set(g) - ledger
    assert h["skipped_steps"] == [0] * len(h["epochs"])
    assert h["rollbacks"] == 0
    assert h["epochs"] == g["epochs"]
    # Full per-epoch trajectory, not just the endpoint.
    for k, tol in (("train_loss", 0.2), ("val_loss", 0.2),
                   ("train_metric", 0.1), ("val_metric", 0.1)):
        for ours, theirs in zip(h[k], g[k]):
            assert abs(ours - theirs) < tol, (k, h[k], g[k])

    loaded = load_model(MLModel(), str(tmp_path))
    test_loader = Loader(datasets[1], batch_size=32, shuffle=True)
    test_loss, test_acc = trainer.test(loaded, test_loader)
    assert abs(float(test_loss) - golden["test_loss"]) < 0.2
    assert abs(float(test_acc) - golden["test_accuracy"]) < 0.1
