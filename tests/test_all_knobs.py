"""All-knobs-on composition (VERDICT r3 #8).

The trainer advertises its throughput/memory knobs as freely composable
(trainer.py docstring): ``steps_per_execution`` and ``grad_accum_steps``
amortize dispatch, ``shard_opt_state`` re-places the moments — none may
change the math.  Pairwise equality is tested elsewhere; this holds ALL
of them on at once — against a run with only the math knobs
(clip + EMA, which do change the update and so must be identical on both
sides) — and round-trips a resume with everything on.
"""

import jax
import numpy as np
import pytest

from ml_trainer_tpu import MLModel, Trainer
from ml_trainer_tpu.data import SyntheticCIFAR10

MATH_KNOBS = dict(grad_clip_norm=0.5, ema_decay=0.9)
PERF_KNOBS = dict(
    steps_per_execution=4, grad_accum_steps=2, shard_opt_state=True,
)

# lr matters here: each perf knob legitimately changes float reduction
# ORDER by a few ULPs per step (scan-carry vs unrolled dispatch, sharded
# vs replicated moment layouts), and at lr=0.01 on random-label data that
# seed noise amplifies ~1e5x over 12 adam+clip steps (measured: identical
# config, spe4 alone, 3 epochs -> 7.6e-4 param drift; lr=0.001 -> 3e-6).
# The equality being asserted is bit-level per-step math, so the test
# runs in a regime where chaos cannot masquerade as a real defect.
LR = 0.002


def _trainer(workdir, epochs, **knobs):
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=128, seed=0),
                  SyntheticCIFAR10(size=32, seed=1)),
        epochs=epochs, batch_size=32, model_dir=str(workdir),
        is_parallel=True, backend="cpu", seed=13, lr=LR,
        optimizer="adam", metric=None, **knobs,
    )


@pytest.mark.slow
def test_all_knobs_on_matches_plain_trajectory(tmp_path):
    plain = _trainer(tmp_path / "plain", 3, **MATH_KNOBS)
    plain.fit()
    knobs = _trainer(tmp_path / "knobs", 3, **MATH_KNOBS, **PERF_KNOBS)
    knobs.fit()
    np.testing.assert_allclose(
        plain.train_losses, knobs.train_losses, rtol=1e-4
    )
    np.testing.assert_allclose(plain.val_losses, knobs.val_losses, rtol=1e-4)
    # Params wear the amplified ULP noise hardest (see LR note above):
    # a real composition bug measured 0.03+ here, noise stays ~2e-4.
    for a, b in zip(
        jax.tree.leaves(plain.state.params), jax.tree.leaves(knobs.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
    for a, b in zip(
        jax.tree.leaves(plain.state.ema_params),
        jax.tree.leaves(knobs.state.ema_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


@pytest.mark.slow
def test_all_knobs_on_resume_roundtrip(tmp_path):
    full = _trainer(tmp_path / "full", 4, **MATH_KNOBS, **PERF_KNOBS)
    full.fit()
    t1 = _trainer(tmp_path / "resume", 2, **MATH_KNOBS, **PERF_KNOBS)
    t1.fit()
    t2 = _trainer(tmp_path / "resume", 4, **MATH_KNOBS, **PERF_KNOBS)
    t2.fit(resume=True)
    assert t2.train_losses[:2] == pytest.approx(t1.train_losses, abs=1e-7)
    np.testing.assert_allclose(
        t2.train_losses, full.train_losses, rtol=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(full.state.params), jax.tree.leaves(t2.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
