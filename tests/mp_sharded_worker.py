"""Worker for the 2-process SHARDED-checkpoint test (format v3).

Each rank is one host of a 2-process CPU cluster (4 virtual devices
each).  Trains with ZeRO-1 + sharded_checkpoint=True, then PROVES the
no-full-tree property from the on-disk piece tables: this process's
pieces for the data-sharded optimizer moments cover exactly its
addressable half of the rows, and the replicated params were written by
exactly one process (replica-0 dedupe).  Then resumes — every host
stitches its own shards back from shared storage; no broadcast, no
gather.

Usage: python mp_sharded_worker.py <coordinator_port> <process_id> <workdir>
"""

import faulthandler
import json
import os
import sys

# A hung collective is this test's failure mode: dump every thread's stack
# (and die) well inside the harness timeout so the report shows WHERE.
faulthandler.dump_traceback_later(150, exit=True)

port, pid, workdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Cross-process CPU computations need the gloo collectives backend (see
# mp_worker.py); must be set before the first device use.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2 and jax.device_count() == 8

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ml_trainer_tpu import MLModel, Trainer  # noqa: E402
from ml_trainer_tpu.checkpoint import checkpoint as ckpt  # noqa: E402
from ml_trainer_tpu.data import SyntheticCIFAR10  # noqa: E402

datasets = (
    SyntheticCIFAR10(size=64, seed=0),
    SyntheticCIFAR10(size=32, seed=1),
)
common = dict(
    batch_size=16, model_dir=workdir, is_parallel=True, backend="cpu",
    seed=5, lr=0.001, optimizer="adam", metric=None,
    shard_opt_state=True, sharded_checkpoint=True,
)

t = Trainer(MLModel(), datasets=datasets, epochs=2, **common)
t.fit()
print(f"LOSSES {t.train_losses}", flush=True)

# --- on-disk proof that this process wrote only its own shards
ckpt_dir = os.path.join(workdir, "checkpoints")
latest = ckpt.latest_checkpoint(ckpt_dir)
assert ckpt.checkpoint_format(latest) == 3
with open(os.path.join(latest, "manifest.json")) as fp:
    manifest = json.load(fp)
with open(os.path.join(latest, f"manifest_p{pid:05d}.json")) as fp:
    mine = json.load(fp)["pieces"]
leaves = manifest["leaves"]
sharded_rows = {}  # leaf id -> rows this process wrote
for e in mine:
    meta = leaves[e["leaf"]]
    dims = meta.get("shape")
    if dims and tuple(meta["path"])[0] == "opt_state" and len(dims) >= 1:
        covered = e["stop"][0] - e["start"][0]
        if covered < dims[0]:  # a genuinely sharded (partial-rows) piece
            sharded_rows[e["leaf"]] = (
                sharded_rows.get(e["leaf"], 0) + covered
            )
assert sharded_rows, "no sharded optimizer-moment pieces written"
for leaf_id, rows in sharded_rows.items():
    total = leaves[leaf_id]["shape"][0]
    assert rows * 2 == total, (
        f"leaf {leaf_id}: process {pid} wrote {rows} of {total} rows — "
        "expected exactly its addressable half"
    )
# Replicated params deduped to one writer across the cluster: count both
# processes' pieces for every params leaf (shared fs: both tables visible).
tables = ckpt._read_piece_tables(latest)
for i, meta in enumerate(leaves):
    if meta.get("shape") is not None and tuple(meta["path"])[0] == "params":
        assert len(tables[i]) == 1, (meta["path"], len(tables[i]))
print("SHARD_LAYOUT_OK", flush=True)

# --- resume: every host stitches from shared storage, no broadcast
t2 = Trainer(MLModel(), datasets=datasets, epochs=3, **common)
t2.fit(resume=True)
assert len(t2.train_losses) == 3
assert t2.train_losses[:2] == t.train_losses, (
    t2.train_losses, t.train_losses,
)
# Params identical across hosts after the sharded restore + 1 epoch.
fp_local = float(
    sum(np.abs(np.asarray(x.addressable_data(0))).sum()
        for x in jax.tree.leaves(t2.state.params))
)
print(f"RESUME_OK {t2.train_losses} fp={fp_local:.6f}", flush=True)
print("WORKER_DONE", flush=True)
