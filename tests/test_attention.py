"""Attention numerics: XLA path invariants + Pallas flash kernel (interpret
mode on the CPU mesh) against the reference einsum implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.ops.attention import (
    attention,
    dot_product_attention,
    flash_attention,
)


def qkv(b=2, h=4, s=128, d=64, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, h, s, d)
    return tuple(
        jnp.asarray(rng.normal(size=shape), dtype=jnp.float32) for _ in range(3)
    )


def test_softmax_rows_sum_to_one_effectively():
    q, k, v = qkv(s=32)
    ones = jnp.ones_like(v)
    out = dot_product_attention(q, k, ones)
    np.testing.assert_allclose(out, np.ones(out.shape), atol=1e-5)


def test_causal_masks_future():
    q, k, v = qkv(s=32)
    out = dot_product_attention(q, k, v, causal=True)
    # Perturb a future value; earlier outputs unchanged.
    v2 = v.at[:, :, 20].add(100.0)
    out2 = dot_product_attention(q, k, v2, causal=True)
    np.testing.assert_allclose(out[:, :, :20], out2[:, :, :20], atol=1e-5)
    assert not np.allclose(out[:, :, 20:], out2[:, :, 20:])


def test_explicit_mask_matches_causal():
    q, k, v = qkv(s=16)
    s = 16
    tri = jnp.tril(jnp.ones((s, s), bool))[None, None]
    np.testing.assert_allclose(
        dot_product_attention(q, k, v, causal=True),
        dot_product_attention(q, k, v, mask=tri),
        atol=1e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = qkv(b=1, h=2, s=256, d=64)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, None, causal, None, 128, 128, True)  # interpret
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_gradients_match_reference():
    q, k, v = qkv(b=1, h=1, s=128, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, None, 64, 64, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


def test_dispatcher_falls_back_on_cpu():
    q, k, v = qkv(s=64)
    out = attention(q, k, v, implementation="auto")  # CPU -> XLA path
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_flash_explicit_request_rejects_mask_and_ragged_lengths():
    q, k, v = qkv(s=64)
    mask = jnp.ones((1, 1, 64, 64), bool)
    with pytest.raises(ValueError, match="causal mask and kv_lens"):
        attention(q, k, v, mask=mask, implementation="flash")
    q2 = q[:, :, :32]
    with pytest.raises(ValueError, match="equal query/key"):
        attention(q2, k, v, causal=True, implementation="flash")


def test_flash_kv_streaming_multiple_blocks():
    """KV now streams through the grid: multiple kv blocks per q block."""
    q, k, v = qkv(b=1, h=1, s=256, d=64)
    out = flash_attention(q, k, v, None, False, None, 64, 32, True)  # 8 kv blocks
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_backward_is_pallas_not_xla_recompute():
    """VERDICT r1 #5: the VJP must be the block-recompute Pallas pair, not a
    recompute through dot_product_attention (O(S^2) memory)."""
    import inspect

    from ml_trainer_tpu.ops import attention as A

    src = inspect.getsource(A._flash_bwd)
    assert "dot_product_attention" not in src
    assert "_flash_backward" in src


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference_uneven_blocks(causal):
    """Backward kernels with block_q != block_k and multiple blocks on both
    grid axes (dQ streams 4 kv blocks; dK/dV streams 2 q blocks)."""
    q, k, v = qkv(b=2, h=2, s=128, d=32)
    g = jnp.asarray(
        np.random.default_rng(7).normal(size=q.shape), jnp.float32
    )
    _, vjp_f = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, None, causal, None, 64, 32, True),
        q, k, v,
    )
    _, vjp_r = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal),
        q, k, v,
    )
    for a, b, name in zip(vjp_f(g), vjp_r(g), "qkv"):
        np.testing.assert_allclose(
            a, b, atol=2e-4, rtol=2e-4, err_msg=f"d{name}"
        )


def test_flash_backward_preserves_dtype():
    q, k, v = qkv(b=1, h=1, s=128, d=64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out, vjp = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, None, True, None, 64, 64, True),
        q, k, v,
    )
    grads = vjp(jnp.ones_like(out))
    assert out.dtype == jnp.bfloat16
    assert all(gr.dtype == jnp.bfloat16 for gr in grads)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kv_lens_matches_masked_reference(causal):
    """VERDICT r2 weak #7: the right-padded mask family (BERT's actual
    inference mode) runs INSIDE the flash kernel.  Values must match the
    XLA path under the equivalent boolean key mask."""
    b, s = 3, 128
    q, k, v = qkv(b=b, h=2, s=s, d=64, seed=3)
    kv_lens = jnp.asarray([s, 70, 1], jnp.int32)  # full / padded / minimal
    mask = (jnp.arange(s)[None, None, None, :] < kv_lens[:, None, None, None])
    ref = dot_product_attention(q, k, v, causal=causal, mask=mask)
    out = flash_attention(q, k, v, kv_lens, causal, None, 64, 32, True)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_kv_lens_gradients_match_reference():
    b, s = 2, 128
    q, k, v = qkv(b=b, h=2, s=s, d=64, seed=4)
    kv_lens = jnp.asarray([s, 50], jnp.int32)
    mask = (jnp.arange(s)[None, None, None, :] < kv_lens[:, None, None, None])

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, kv_lens, False, None, 64, 32, True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, mask=mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(a, b_, atol=5e-2, rtol=2e-3)
    # Padded key positions get exactly zero dK/dV.
    np.testing.assert_allclose(np.asarray(gf[1][1, :, 50:]), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(gf[2][1, :, 50:]), 0.0, atol=1e-7)


def test_attention_dispatcher_kv_lens_xla_fallback_masks():
    """Off-TPU (or flash-unsupported shapes) the dispatcher must build the
    equivalent boolean mask from kv_lens — padding is never silently
    dropped."""
    q, k, v = qkv(b=2, h=2, s=48, d=32, seed=5)  # 48 % 128 != 0 -> XLA path
    kv_lens = jnp.asarray([48, 20], jnp.int32)
    mask = (jnp.arange(48)[None, None, None, :] < kv_lens[:, None, None, None])
    np.testing.assert_allclose(
        attention(q, k, v, kv_lens=kv_lens),
        dot_product_attention(q, k, v, mask=mask),
        atol=1e-5,
    )


def test_bert_right_padded_flag_equivalence():
    """right_padded=True (kv_lens fused path) and False (boolean-mask XLA
    path) must agree on a right-padded batch."""
    from ml_trainer_tpu.models.bert import BertEncoder

    ids = np.zeros((2, 32), np.int32)
    ids[0, :32] = np.arange(1, 33)
    ids[1, :10] = np.arange(1, 11)  # right-padded with pad_token_id=0
    ids = jnp.asarray(ids)
    kw = dict(vocab_size=64, max_len=32, embed_dim=32, depth=2, num_heads=2,
              mlp_dim=64, num_classes=2)
    m_fast = BertEncoder(right_padded=True, **kw)
    m_exact = BertEncoder(right_padded=False, **kw)
    variables = m_fast.init({"params": jax.random.PRNGKey(0)}, ids, train=False)
    out_fast = m_fast.apply(variables, ids, train=False)
    out_exact = m_exact.apply(variables, ids, train=False)
    np.testing.assert_allclose(out_fast, out_exact, atol=1e-4, rtol=1e-4)


def test_flash_bf16_matches_reference():
    """The north-star configs run bf16 activations; the kernel must hold
    its accuracy with bf16 inputs (f32 accumulation inside)."""
    q, k, v = qkv(b=1, h=2, s=128, d=64, seed=6)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    ref = dot_product_attention(
        qb.astype(jnp.float32), kb.astype(jnp.float32),
        vb.astype(jnp.float32), causal=True,
    )
    out = flash_attention(qb, kb, vb, None, True, None, 64, 64, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref, atol=2e-2, rtol=2e-2
    )


def test_flash_inside_shard_map_matches_dense():
    """The ulysses 'auto' path runs the flash kernel INSIDE shard_map on
    TPU; rehearse the composition on the CPU mesh (interpret-mode kernel
    under shard_map over the sequence axis after an all-to-all)."""
    from jax.sharding import PartitionSpec as P

    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.compat import shard_map

    mesh = create_mesh({"sequence": 4}, devices=jax.devices()[:4])
    q, k, v = qkv(b=2, h=4, s=256, d=64, seed=7)

    def local(q, k, v):
        # Ulysses layout: heads scattered, sequence gathered; each shard
        # then runs an ordinary full-sequence flash attention.
        a2a = lambda t: jax.lax.all_to_all(
            t, "sequence", split_axis=1, concat_axis=2, tiled=True
        )
        out = flash_attention(a2a(q), a2a(k), a2a(v), None, True, None,
                              64, 64, True)
        return jax.lax.all_to_all(
            out, "sequence", split_axis=2, concat_axis=1, tiled=True
        )

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sequence"),) * 3,
        out_specs=P(None, None, "sequence"),
        check_vma=False,
    )
    out = jax.jit(fn)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_padded_off_tile_shapes_match_reference(causal):
    """VERDICT r2 weak #7 (remaining half): off-tile shapes — a ViT-like
    sequence (197) and a head_dim that is not a multiple of 64 — run the
    kernel through the zero-padding wrapper with exact-math results."""
    from ml_trainer_tpu.ops.attention import _flash_padded

    q, k, v = qkv(b=2, h=2, s=197, d=48, seed=8)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = _flash_padded(q, k, v, None, causal, None, 128, 128, interpret=True)
    assert out.shape == q.shape
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_padded_respects_kv_lens():
    from ml_trainer_tpu.ops.attention import _flash_padded

    s = 100
    q, k, v = qkv(b=2, h=2, s=s, d=32, seed=9)
    kv_lens = jnp.asarray([s, 37], jnp.int32)
    mask = (jnp.arange(s)[None, None, None, :] < kv_lens[:, None, None, None])
    ref = dot_product_attention(q, k, v, mask=mask)
    out = _flash_padded(q, k, v, kv_lens, False, None, 128, 128,
                        interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_flash_padded_gradients_match_reference():
    """Padded query rows receive zero cotangent through the slice VJP and
    padded keys are masked, so gradients must equal the dense reference
    on the real region — and carry no NaNs from the padding."""
    from ml_trainer_tpu.ops.attention import _flash_padded

    q, k, v = qkv(b=1, h=2, s=77, d=40, seed=10)

    def loss_flash(q, k, v):
        return jnp.sum(
            _flash_padded(q, k, v, None, True, None, 64, 64,
                          interpret=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gr, "qkv"):
        assert np.isfinite(np.asarray(a)).all(), f"d{name} has non-finite"
        np.testing.assert_allclose(a, b_, atol=2e-3, rtol=2e-3,
                                   err_msg=f"d{name}")


def test_auto_dispatch_pads_only_long_off_tile_sequences(monkeypatch):
    """'auto' takes: exact flash on tile-aligned shapes, the padding
    wrapper only from _AUTO_PAD_MIN_SEQ up, XLA below it."""
    import ml_trainer_tpu.ops.attention as A

    calls = []

    def fake_flash(q, k, v, kv_lens, causal, scale, block_q, block_k,
                   interpret):
        calls.append("exact")
        return dot_product_attention(q, k, v, causal=causal)

    def fake_padded(q, k, v, kv_lens, causal, scale, block_q, block_k,
                    interpret=False):
        calls.append("padded")
        return dot_product_attention(q, k, v, causal=causal)

    monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(A, "flash_attention", fake_flash)
    monkeypatch.setattr(A, "_flash_padded", fake_padded)

    q, k, v = qkv(b=1, h=1, s=256, d=64, seed=11)
    A.attention(q, k, v, causal=True)               # tile-aligned
    q2, k2, v2 = qkv(b=1, h=1, s=1100, d=64, seed=11)
    A.attention(q2, k2, v2, causal=True)            # long off-tile
    q3, k3, v3 = qkv(b=1, h=1, s=197, d=64, seed=11)
    out = A.attention(q3, k3, v3, causal=True)      # short off-tile -> XLA
    assert calls == ["exact", "padded"]
    np.testing.assert_allclose(
        out, dot_product_attention(q3, k3, v3, causal=True), atol=1e-5
    )


def test_flash_padded_head_dim_only_keeps_unmasked_variant():
    """d-only padding must not fabricate a lens array (the masked kernel
    variant costs an SMEM operand + per-block keep mask for nothing)."""
    from unittest import mock

    import ml_trainer_tpu.ops.attention as A

    q, k, v = qkv(b=1, h=1, s=128, d=48, seed=12)
    with mock.patch.object(
        A, "flash_attention", wraps=A.flash_attention
    ) as spy:
        out = A._flash_padded(q, k, v, None, True, None, 64, 64,
                              interpret=True)
    assert spy.call_args[0][3] is None  # kv_lens stayed None
    np.testing.assert_allclose(
        out, dot_product_attention(q, k, v, causal=True),
        atol=2e-3, rtol=2e-3,
    )
