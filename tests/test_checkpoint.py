"""Checkpoint layer: v2 per-leaf directory format (VERDICT r1 #9) — no
monolithic pickle, async writes, legacy v1 compatibility."""

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ml_trainer_tpu.checkpoint import checkpoint as ckpt
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.ops import get_optimizer
from ml_trainer_tpu.train_state import TrainState


def make_state(seed=0):
    model = get_model("gpt2_tiny")
    ids = jnp.ones((1, 16), jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(seed)}, ids, train=False)
    tx = get_optimizer("adamw", 1e-3)
    params = variables["params"]
    return TrainState(
        step=jnp.asarray(7, jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={},
        rng=jax.random.PRNGKey(1),
    )


def assert_states_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_v2_roundtrip_no_pickle(tmp_path):
    state = make_state()
    history = {"train_loss": [1.0, 0.5], "metric_type": None}
    path = ckpt.save_checkpoint(str(tmp_path), state, history, epoch=3)
    assert os.path.isdir(path)  # directory, not a .pkl blob
    assert os.path.exists(os.path.join(path, "manifest.json"))
    assert not any(f.endswith(".pkl") for f in os.listdir(tmp_path))
    template = make_state(seed=9)
    restored, h, epoch = ckpt.restore_checkpoint(path, template)
    assert epoch == 3 and h["train_loss"] == [1.0, 0.5]
    assert_states_equal(state, restored)
    assert int(restored.step) == 7


def test_async_write_and_wait(tmp_path):
    state = make_state()
    path = ckpt.save_checkpoint(
        str(tmp_path), state, {"train_loss": []}, epoch=1, block=False
    )
    ckpt.wait_for_checkpoints()
    assert os.path.isdir(path)
    restored, _, _ = ckpt.restore_checkpoint(path, make_state(seed=4))
    assert_states_equal(state, restored)


def test_prune_and_latest_mixed_formats(tmp_path):
    state = make_state()
    # A legacy v1 pickle checkpoint alongside v2 dirs.
    from flax import serialization

    legacy = os.path.join(str(tmp_path), "checkpoint_1.pkl")
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(legacy, "wb") as fp:
        pickle.dump(
            {
                "state": serialization.to_state_dict(jax.device_get(state)),
                "history": {"train_loss": [9.0]},
                "epoch": 1,
            },
            fp,
        )
    # Legacy restore still works.
    restored, h, epoch = ckpt.restore_checkpoint(legacy, make_state(seed=2))
    assert epoch == 1 and h["train_loss"] == [9.0]
    assert_states_equal(state, restored)

    for e in (2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), state, {}, epoch=e, keep=3)
    # keep=3 pruned the oldest (the legacy pkl).
    assert not os.path.exists(legacy)
    latest = ckpt.latest_checkpoint(str(tmp_path))
    assert latest.endswith("checkpoint_4")


def test_large_state_streams_per_leaf(tmp_path):
    """Every leaf is its own .npy — no single file holds the whole state."""
    state = make_state()
    path = ckpt.save_checkpoint(str(tmp_path), state, {}, epoch=1)
    leaves = [f for f in os.listdir(path) if f.endswith(".npy")]
    n_state_leaves = len(jax.tree.leaves(state))
    assert len(leaves) == n_state_leaves
    total = sum(os.path.getsize(os.path.join(path, f)) for f in leaves)
    biggest = max(os.path.getsize(os.path.join(path, f)) for f in leaves)
    assert biggest < total  # genuinely split across files


def test_crashed_inflight_write_never_shadows_last_good(tmp_path):
    """Failure-recovery contract: a write that died mid-flight (its .tmp
    dir never renamed) is invisible to latest_checkpoint, restore uses the
    last COMPLETE checkpoint, and a clean retry of the same epoch replaces
    the debris."""
    state = make_state()
    good = ckpt.save_checkpoint(
        str(tmp_path), state, {"train_loss": [1.0], "metric_type": None},
        epoch=1,
    )
    ckpt.wait_for_checkpoints()
    # Simulate the crash: a partially-written epoch-2 tmp dir (some leaves
    # on disk, no manifest rename).
    debris = os.path.join(str(tmp_path), ckpt.CHECKPOINT_PREFIX + "2.tmp")
    os.makedirs(debris)
    with open(os.path.join(debris, "leaf_000.npy"), "wb") as f:
        f.write(b"\x93NUMPY garbage")
    assert ckpt.latest_checkpoint(str(tmp_path)) == good
    restored, h, epoch = ckpt.restore_checkpoint(
        ckpt.latest_checkpoint(str(tmp_path)), make_state(seed=9)
    )
    assert epoch == 1
    assert_states_equal(state, restored)
    # Retrying the crashed epoch cleans the debris and lands atomically.
    path2 = ckpt.save_checkpoint(
        str(tmp_path), state, {"train_loss": [1.0, 0.7], "metric_type": None},
        epoch=2,
    )
    ckpt.wait_for_checkpoints()
    assert not os.path.exists(debris)
    assert ckpt.latest_checkpoint(str(tmp_path)) == path2


def test_restore_pre_decay_mask_checkpoint():
    """Checkpoints written before the optimizer factory always passed a
    weight-decay mask lack the MaskedState levels; the compat shim must
    inject them so old checkpoints keep resuming."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import serialization

    from ml_trainer_tpu.checkpoint.checkpoint import _from_state_dict_compat
    from ml_trainer_tpu.ops import get_optimizer
    from ml_trainer_tpu.train_state import TrainState

    params = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,))}

    def make_state(tx):
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=optax.chain(optax.identity(), tx).init(params),
            batch_stats={}, rng=jax.random.PRNGKey(0),
        )

    # Old writer: bare optax.adamw (no mask -> no MaskedState level).
    old_sd = serialization.to_state_dict(
        make_state(optax.adamw(0.1, weight_decay=0.1))
    )
    # New reader: factory optimizer (mask always present).
    template = make_state(get_optimizer("adamw", 0.1, weight_decay=0.1))
    restored = _from_state_dict_compat(template, old_sd)
    assert (
        jax.tree_util.tree_structure(restored)
        == jax.tree_util.tree_structure(template)
    )
    # And a new-format state dict round-trips untouched.
    new_sd = serialization.to_state_dict(template)
    round_trip = _from_state_dict_compat(template, new_sd)
    assert (
        jax.tree_util.tree_structure(round_trip)
        == jax.tree_util.tree_structure(template)
    )


def test_torch_export_roundtrip_and_forward_parity(tmp_path):
    """save_torch_checkpoint is the exact inverse of the import, AND the
    exported weights drive a real torch LeNet to the SAME outputs as the
    flax model — migration runs in both directions
    (ref: src/model.py:7-24, src/utils/utils.py:15-28)."""
    import pytest

    torch = pytest.importorskip("torch")
    import torch.nn as tnn
    import torch.nn.functional as F

    from ml_trainer_tpu.checkpoint import (
        load_torch_checkpoint,
        save_torch_checkpoint,
    )
    from ml_trainer_tpu.models import MLModel

    model = MLModel()
    x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
    variables = model.init(
        {"params": jax.random.PRNGKey(3)}, jnp.asarray(x), train=False
    )
    path = str(tmp_path / "model.pth")
    save_torch_checkpoint(path, variables)

    # Round trip: import(export(params)) == params, leaf for leaf.
    back = load_torch_checkpoint(path)

    def by_path(tree):
        return {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_leaves_with_path(tree)
        }

    orig_leaves, back_leaves = by_path(variables["params"]), by_path(back)
    assert orig_leaves.keys() == back_leaves.keys()
    for key in orig_leaves:
        np.testing.assert_array_equal(
            np.asarray(orig_leaves[key]), np.asarray(back_leaves[key])
        )

    # Forward parity: the reference's torch LeNet (ref: src/model.py:7-24)
    # loaded from the export must produce the flax model's exact outputs.
    class TorchLeNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, 6, 5)
            self.conv2 = tnn.Conv2d(6, 16, 5)
            self.fc1 = tnn.Linear(16 * 5 * 5, 120)
            self.fc2 = tnn.Linear(120, 84)
            self.fc3 = tnn.Linear(84, 10)

        def forward(self, x):
            x = F.max_pool2d(F.relu(self.conv1(x)), 2)
            x = F.max_pool2d(F.relu(self.conv2(x)), 2)
            x = torch.flatten(x, 1)
            x = F.relu(self.fc1(x))
            x = F.relu(self.fc2(x))
            return self.fc3(x)

    tmodel = TorchLeNet()
    tmodel.load_state_dict(torch.load(path, weights_only=True))
    tmodel.eval()
    with torch.no_grad():
        torch_out = tmodel(
            torch.from_numpy(x.transpose(0, 3, 1, 2))  # NHWC -> NCHW
        ).numpy()
    flax_out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(flax_out, torch_out, atol=1e-5)

    # The DDP-prefixed form loads through the same strip path the
    # reference's load_model uses — compare KEYS too, or a broken prefix
    # strip would leave 'module/...' layer names with identical leaf
    # values and the test would still pass.
    save_torch_checkpoint(
        str(tmp_path / "ddp.pth"), variables, ddp_prefix=True
    )
    back_ddp_leaves = by_path(load_torch_checkpoint(str(tmp_path / "ddp.pth")))
    assert back_ddp_leaves.keys() == orig_leaves.keys()
    for key in orig_leaves:
        np.testing.assert_array_equal(
            np.asarray(back_ddp_leaves[key]), np.asarray(orig_leaves[key])
        )


def test_trainer_export_torch_public_api(tmp_path):
    """Trainer.export_torch writes a .pth the import path reads back with
    the trained values (the MIGRATION.md flow, public surface)."""
    import pytest

    pytest.importorskip("torch")
    from ml_trainer_tpu import MLModel, Trainer
    from ml_trainer_tpu.checkpoint import load_torch_checkpoint
    from ml_trainer_tpu.data import SyntheticCIFAR10

    t = Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=32, seed=0),
                  SyntheticCIFAR10(size=16, seed=1)),
        epochs=1, batch_size=16, model_dir=str(tmp_path), metric=None,
        optimizer="adam", lr=0.001,
    )
    t.fit()
    path = t.export_torch(str(tmp_path / "out.pth"))
    back = load_torch_checkpoint(path)
    # Keyed comparison (not zipped leaves): a dropped/misnamed layer must
    # FAIL here, not silently truncate the zip.
    def by_path(tree):
        return {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_leaves_with_path(tree)
        }

    orig, round_tripped = by_path(t.state.params), by_path(back)
    assert orig.keys() == round_tripped.keys()
    for key in orig:
        np.testing.assert_array_equal(
            np.asarray(orig[key]), np.asarray(round_tripped[key])
        )
