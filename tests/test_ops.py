"""Unit tests for the ops registries: optimizers, schedules, losses,
metrics, prediction functions (the reference's factory methods,
ref: src/trainer.py:115-172)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.ops import (
    get_criterion,
    get_metric,
    get_optimizer,
    get_prediction_function,
    get_predictions,
    make_lr_schedule,
    PlateauController,
)


# --------------------------------------------------------------- optimizers
@pytest.mark.parametrize(
    "name",
    ["sgd", "adam", "adagrad", "adamax", "adamw", "lamb", "lion",
     "adafactor"],
)
def test_optimizer_step_changes_params(name):
    tx = get_optimizer(name, 0.1, momentum=0.9, weight_decay=0.01)
    params = {"w": jnp.ones((3,))}
    state = tx.init(params)
    grads = {"w": jnp.full((3,), 0.5)}
    updates, _ = tx.update(grads, state, params)
    new = jax.tree.map(lambda p, u: p + u, params, updates)
    assert not np.allclose(new["w"], params["w"])


def test_adafactor_factors_second_moment():
    """The reason adafactor is in the registry: a [m, n] kernel's second
    moment is stored as row + column vectors (O(m+n)), not a full [m, n]
    matrix like adam's nu — the LM-pretraining memory win.  (Factoring
    engages for dims >= optax's min_dim_size_to_factor=128, i.e. the
    transformer-kernel sizes it exists for.)"""
    m, n = 256, 512
    params = {"w": jnp.ones((m, n))}
    count = lambda tree: sum(  # noqa: E731
        np.prod(leaf.shape)
        for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "shape")
    )
    ada = count(get_optimizer("adafactor", 0.1).init(params))
    adam = count(get_optimizer("adam", 0.1).init(params))
    assert adam >= 2 * m * n  # mu + nu, both full
    assert ada < m * n  # factored: no full-matrix buffer at all


def test_sgd_matches_torch_semantics():
    """Coupled weight decay + momentum must follow torch.optim.SGD
    (ref: src/trainer.py:124-126)."""
    import torch

    w0, g, lr, mom, wd = 1.5, 0.3, 0.1, 0.9, 0.05
    tw = torch.nn.Parameter(torch.tensor([w0]))
    topt = torch.optim.SGD([tw], lr=lr, momentum=mom, weight_decay=wd)
    tx = get_optimizer("sgd", lr, momentum=mom, weight_decay=wd)
    params = {"w": jnp.asarray([w0])}
    state = tx.init(params)
    for _ in range(3):
        topt.zero_grad()
        tw.grad = torch.tensor([g])
        topt.step()
        updates, state = tx.update({"w": jnp.asarray([g])}, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
    assert np.allclose(params["w"], tw.detach().numpy(), atol=1e-6)


def test_unknown_optimizer_raises():
    with pytest.raises(ValueError):
        get_optimizer("rmspropp", 0.1)


def test_lion_uses_single_moment_buffer():
    """The reason lion is in the registry: half the optimizer HBM of the
    Adam family (one sign-momentum buffer, no second moment)."""
    params = {"w": jnp.ones((4,))}
    count = lambda tree: sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(tree)
        if hasattr(x, "shape") and x.shape
    )
    lion_state = get_optimizer("lion", 0.1).init(params)
    adam_state = get_optimizer("adamw", 0.1).init(params)
    assert count(lion_state) == count(adam_state) // 2


# ---------------------------------------------------------------- schedules
def test_constant_schedule():
    sched = make_lr_schedule(None, 0.01, steps_per_epoch=10)
    assert np.isclose(float(sched(0)), 0.01)
    assert np.isclose(float(sched(999)), 0.01)


def test_cosine_warm_restarts_matches_torch():
    """Per-batch fractional stepping (ref: src/trainer.py:189-190) against
    torch.optim.lr_scheduler.CosineAnnealingWarmRestarts(T_0=5, eta_min=1e-7)."""
    import torch

    base_lr, spe = 0.1, 4
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=base_lr)
    tsched = torch.optim.lr_scheduler.CosineAnnealingWarmRestarts(
        opt, T_0=5, eta_min=1e-7
    )
    sched = make_lr_schedule("CosineAnnealingWarmRestarts", base_lr, spe)
    for epoch in range(1, 8):
        for i in range(spe):
            step = (epoch - 1) * spe + i
            tsched.step(epoch - 1 + i / spe)
            assert np.isclose(
                float(sched(step)), opt.param_groups[0]["lr"], atol=1e-9
            ), (epoch, i)


def test_step_lr_decays_every_two_epochs():
    sched = make_lr_schedule("StepLR", 1.0, steps_per_epoch=10)
    assert np.isclose(float(sched(0)), 1.0)  # epoch 1
    assert np.isclose(float(sched(15)), 1.0)  # epoch 2
    assert np.isclose(float(sched(20)), 0.1)  # epoch 3
    assert np.isclose(float(sched(45)), 0.01)  # epoch 5


def test_plateau_controller_reduces_after_patience():
    ctl = PlateauController(base_lr=1.0, patience=2, factor=0.1)
    assert ctl.update(1.0) == 1.0
    for _ in range(2):
        ctl.update(1.0)
    assert ctl.update(1.0) == pytest.approx(0.1)


def test_unknown_scheduler_raises():
    with pytest.raises(ValueError):
        make_lr_schedule("OneCycle", 0.1, 10)


# ------------------------------------------------------------------- losses
def test_cross_entropy_matches_torch():
    import torch

    logits = np.random.default_rng(0).normal(size=(8, 10)).astype(np.float32)
    targets = np.arange(8) % 10
    ours = float(get_criterion("cross_entropy")(jnp.asarray(logits), jnp.asarray(targets)))
    theirs = float(
        torch.nn.CrossEntropyLoss()(torch.tensor(logits), torch.tensor(targets))
    )
    assert np.isclose(ours, theirs, atol=1e-6)


def test_nll_and_l1_l2_and_custom():
    rng = np.random.default_rng(1)
    logp = jnp.log(jax.nn.softmax(jnp.asarray(rng.normal(size=(4, 5)), dtype=jnp.float32)))
    y = jnp.asarray([0, 1, 2, 3])
    nll = float(get_criterion("neg-loss")(logp, y))
    assert nll > 0
    a = jnp.asarray(rng.normal(size=(6,)), dtype=jnp.float32)
    b = jnp.asarray(rng.normal(size=(6,)), dtype=jnp.float32)
    assert np.isclose(float(get_criterion("l1")(a, b)), float(jnp.mean(jnp.abs(a - b))))
    l2 = float(get_criterion("l2")(a, b))
    custom = float(get_criterion("custom")(a, b))
    assert np.isclose(l2, custom)  # custom IS mse (ref: src/utils/functions.py:15-17)


def test_unknown_criterion_raises():
    with pytest.raises(ValueError):
        get_criterion("huber")


# ------------------------------------------------------------------ metrics
def test_accuracy_on_device():
    outputs = jnp.asarray([[2.0, 1.0], [0.0, 3.0], [5.0, 0.0], [0.0, 1.0]])
    targets = jnp.asarray([0, 1, 1, 1])
    metric = get_metric("accuracy", get_prediction_function("softmax"))
    assert float(metric(outputs, targets)) == pytest.approx(0.75)


def test_mcrmse_matches_reference_math():
    """Mean column-wise RMSE (ref: src/trainer.py:161-163)."""
    rng = np.random.default_rng(2)
    out = rng.normal(size=(16, 3)).astype(np.float32)
    tgt = rng.normal(size=(16, 3)).astype(np.float32)
    expected = np.mean(np.sqrt(np.mean((tgt - out) ** 2, axis=0)))
    metric = get_metric("mcrmse")
    assert np.isclose(float(metric(jnp.asarray(out), jnp.asarray(tgt))), expected, atol=1e-6)


def test_metric_none_disabled():
    assert get_metric(None) is None


def test_f1_matches_sklearn():
    sklearn = pytest.importorskip("sklearn")  # not a declared dependency
    from sklearn.metrics import f1_score

    assert sklearn is not None

    rng = np.random.default_rng(3)
    out = rng.normal(size=(64, 2)).astype(np.float32)
    tgt = rng.integers(0, 2, size=(64,))
    preds = out.argmax(-1)
    metric = get_metric("f1", get_prediction_function("softmax"))
    got = float(metric(jnp.asarray(out), jnp.asarray(tgt)))
    assert got == pytest.approx(f1_score(tgt, preds), abs=1e-6)
    # No positives anywhere -> 0 by convention, not NaN.
    zeros = jnp.asarray([[1.0, 0.0]] * 4)
    assert float(metric(zeros, jnp.zeros((4,), jnp.int32))) == 0.0


def test_top5_accuracy():
    rng = np.random.default_rng(4)
    out = rng.normal(size=(32, 10)).astype(np.float32)
    tgt = rng.integers(0, 10, size=(32,))
    expected = np.mean([
        t in np.argsort(o)[-5:] for o, t in zip(out, tgt)
    ])
    metric = get_metric("top5_accuracy")
    assert float(metric(jnp.asarray(out), jnp.asarray(tgt))) == pytest.approx(
        expected
    )


def test_perplexity_uniform_is_vocab_size():
    """Uniform logits predict every token with prob 1/V -> ppl == V.
    The metric ACCUMULATES mean NLL; the engine's epoch finalizer
    exponentiates once — exp(mean nll), not mean(exp(nll)): averaging
    per-batch perplexities would Jensen-inflate the corpus number."""
    v = 17
    out = jnp.zeros((2, 8, v))
    tgt = jnp.ones((2, 8), jnp.int32)
    metric = get_metric("perplexity")
    per_batch = float(metric(out, tgt))
    assert per_batch == pytest.approx(np.log(v), rel=1e-5)  # mean NLL
    assert float(metric.finalize(per_batch)) == pytest.approx(v, rel=1e-5)
    # Two unequal-difficulty batches: finalize(mean) is the corpus ppl.
    nlls = [1.0, 3.0]
    corpus = float(metric.finalize(np.mean(nlls)))
    assert corpus == pytest.approx(np.exp(2.0))
    assert corpus < np.mean([np.exp(x) for x in nlls])  # Jensen gap


# -------------------------------------------------------------- predictions
def test_prediction_functions():
    x = jnp.asarray([[1.0, 3.0, 2.0]])
    for name in ("softmax", "logsoftmax", None):
        fn = get_prediction_function(name)
        assert int(get_predictions(x, fn)[0]) == 1
    assert get_prediction_function(None) is None


def test_warmup_cosine_schedule():
    sched = make_lr_schedule("WarmupCosine", 1.0, 10, total_steps=200)
    # 5% warmup = 10 steps: linear ramp, peak at the boundary, ~0 at end.
    assert float(sched(0)) == 0.0
    assert np.isclose(float(sched(5)), 0.5, atol=0.06)
    assert np.isclose(float(sched(10)), 1.0, atol=1e-6)
    assert float(sched(200)) < 1e-6
    # Monotone decay after warmup.
    vals = [float(sched(s)) for s in range(10, 201, 10)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))


def test_warmup_linear_schedule():
    sched = make_lr_schedule("WarmupLinear", 2.0, 10, total_steps=100)
    assert float(sched(0)) == 0.0
    assert np.isclose(float(sched(5)), 2.0, atol=1e-6)  # warmup=5 steps
    mid = float(sched(52))  # ~halfway through the 95-step decay
    assert 0.9 < mid < 1.1
    assert float(sched(100)) < 1e-6


def test_decay_mask_skips_biases_and_norms():
    """With decay_mask_matrices_only, weight decay moves matrices but not
    1-D params (biases / LayerNorm scales), for both the decoupled
    (adamw) and coupled (sgd) families."""
    from ml_trainer_tpu.ops.optimizers import decay_mask_matrices_only

    params = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,))}
    zeros = jax.tree.map(jnp.zeros_like, params)
    for name in ("adamw", "sgd"):
        tx = get_optimizer(name, 0.1, momentum=0.0, weight_decay=0.1,
                           decay_mask=decay_mask_matrices_only)
        state = tx.init(params)
        updates, _ = tx.update(zeros, state, params)
        assert not np.allclose(updates["w"], 0.0), name
        np.testing.assert_allclose(updates["b"], 0.0, err_msg=name)
        # Unmasked: both decay.
        tx_all = get_optimizer(name, 0.1, momentum=0.0, weight_decay=0.1)
        updates_all, _ = tx_all.update(zeros, tx_all.init(params), params)
        assert not np.allclose(updates_all["b"], 0.0), name


def test_decay_mask_does_not_change_opt_state_structure():
    """A mask is always passed (all-True default), so toggling the
    exclusion cannot change the opt_state pytree — the checkpoint/resume
    invariant the trainer keeps for grad clipping."""
    from ml_trainer_tpu.ops.optimizers import decay_mask_matrices_only

    params = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,))}
    for name in ("adamw", "sgd", "lion"):
        s_default = get_optimizer(name, 0.1, weight_decay=0.1).init(params)
        s_masked = get_optimizer(
            name, 0.1, weight_decay=0.1,
            decay_mask=decay_mask_matrices_only,
        ).init(params)
        assert (
            jax.tree_util.tree_structure(s_default)
            == jax.tree_util.tree_structure(s_masked)
        ), name


def test_label_smoothing_matches_manual_formula():
    """smoothed CE == (1-s)*CE(target) + s*mean-over-classes CE, i.e. the
    cross entropy against the mixed distribution; s=0 is the plain fn."""
    from ml_trainer_tpu.ops.losses import cross_entropy, cross_entropy_smoothed

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(8, 5)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 5, 8), jnp.int32)
    s = 0.1
    smoothed = cross_entropy_smoothed(s)(logits, targets)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, targets[:, None], axis=-1)[:, 0]
    manual = -jnp.mean(
        (1 - s) * picked + (s / 5) * jnp.sum(logp, axis=-1)
    )
    np.testing.assert_allclose(smoothed, manual, rtol=1e-6)
    assert cross_entropy_smoothed(0.0) is cross_entropy
    # torch-legal degenerate bound accepted; out-of-range rejected.
    assert np.isfinite(float(cross_entropy_smoothed(1.0)(logits, targets)))
    with pytest.raises(ValueError, match="label_smoothing"):
        cross_entropy_smoothed(1.5)
    with pytest.raises(ValueError, match="cross_entropy"):
        get_criterion("l2", label_smoothing=0.1)
