"""In-tree tokenizer tests: byte-level BPE + WordPiece vs the public
implementations loading the SAME committed fixture files.

``transformers``' slow tokenizers accept local vocab files directly
(no download), so they are the parity oracle: any divergence in the
pre-tokenizer scanner, the merge loop, or the greedy WordPiece matcher
fails here.  The fixtures are REAL (BPE trained by
scripts/make_tokenizer_fixtures.py on its embedded corpus), committed
under tests/fixtures/tokenizers/ in the exact GPT-2/BERT file formats —
dropping in the public pretrained files upgrades the data path with no
code change."""

import os

import numpy as np
import pytest

from ml_trainer_tpu.data.tokenizers import (
    ByteLevelBPETokenizer,
    WordPieceTokenizer,
    encode_batch,
    load_tokenizer,
    pretokenize,
)

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "tokenizers")

TRICKY = [
    "The quick brown fox jumps over the lazy dog.",
    "It's training time: don't stop, we're watching!",
    "  leading spaces and   interior runs",
    "trailing space ",
    "numbers 123 and 2026, symbols #@! and mixed bf16 v5e",
    "newlines\n\nand\ttabs\t end\n",
    "unicode: naïve café ümlaut",
    "we're they've I'll he'd she's",
    "word",
    "",
    # The apostrophe of a contraction FOLLOWING punctuation belongs to
    # the symbol run ("..'", "s") — a real divergence once missed.
    "..'s wait!'t and #'d",
    # Control-but-Python-isspace chars: BERT drops them (fusing
    # neighbors); GPT-2 treats them as whitespace-class.
    "a\x0bb cat\x0csat\x85end",
]


def _bpe():
    return ByteLevelBPETokenizer.from_files(
        os.path.join(FIX, "vocab.json"), os.path.join(FIX, "merges.txt")
    )


def _wp():
    return WordPieceTokenizer.from_files(os.path.join(FIX, "vocab.txt"))


# ------------------------------------------------------------------ BPE
def test_bpe_roundtrip_is_lossless():
    """Byte-level coverage: decode(encode(s)) == s for ANY text,
    including strings full of symbols the training corpus never saw."""
    tok = _bpe()
    for s in TRICKY + ["völlig unbekannte Zeichen: 中文 ☃ \x07"]:
        assert tok.decode(tok.encode(s)) == s


def test_bpe_parity_with_transformers_slow():
    transformers = pytest.importorskip("transformers")
    ref = transformers.GPT2Tokenizer(
        vocab_file=os.path.join(FIX, "vocab.json"),
        merges_file=os.path.join(FIX, "merges.txt"),
        unk_token="<unk>",
    )
    tok = _bpe()
    for s in TRICKY:
        assert tok.encode(s) == ref.encode(
            s, add_special_tokens=False
        ), f"BPE divergence on {s!r}"


def test_pretokenizer_matches_gpt2_regex():
    regex = pytest.importorskip("regex")
    pat = regex.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
        r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
    )
    for s in TRICKY:
        assert pretokenize(s) == pat.findall(s), f"scanner vs regex: {s!r}"


def test_bpe_merges_actually_fire():
    """The fixture vocab must produce MULTI-BYTE tokens on corpus-like
    text (a vacuous 1-char-per-token pass would still round-trip)."""
    tok = _bpe()
    ids = tok.encode("the training model")
    assert len(ids) < len("the training model")  # fewer tokens than bytes
    assert any(len(tok.inv_vocab[i]) >= 3 for i in ids)


# ------------------------------------------------------------- WordPiece
def test_wordpiece_parity_with_transformers_slow():
    transformers = pytest.importorskip("transformers")
    ref = transformers.BertTokenizer(
        vocab_file=os.path.join(FIX, "vocab.txt"), do_lower_case=True
    )
    tok = _wp()
    for s in TRICKY:
        assert tok.tokenize(s) == ref.tokenize(s), f"WP tokens: {s!r}"
        assert tok.encode(s) == ref.encode(
            s, add_special_tokens=True
        ), f"WP ids: {s!r}"


def test_wordpiece_known_encoding():
    """Fixture-pinned behavior: known words split greedily, unknown
    words become [UNK], specials frame the sequence."""
    tok = _wp()
    pieces = tok.tokenize("the training xyzzyq!")
    assert pieces[0] == "the"
    assert "[UNK]" in pieces or all(p in tok.vocab for p in pieces)
    ids = tok.encode("the training")
    assert ids[0] == tok.cls_id and ids[-1] == tok.sep_id


def test_parity_fuzz_both_tokenizers():
    """200 random strings over a hostile alphabet (contractions, CJK,
    accents, whitespace runs, digits glued to letters) through BOTH
    implementations vs their transformers oracles."""
    import random

    transformers = pytest.importorskip("transformers")
    gref = transformers.GPT2Tokenizer(
        vocab_file=os.path.join(FIX, "vocab.json"),
        merges_file=os.path.join(FIX, "merges.txt"), unk_token="<unk>",
    )
    bref = transformers.BertTokenizer(
        vocab_file=os.path.join(FIX, "vocab.txt"), do_lower_case=True
    )
    bpe, wp = _bpe(), _wp()
    # 's'/'t'/'d' let the fuzzer form contractions after punctuation
    # ("..'s" — the apostrophe belongs to the SYMBOL run, a real
    # divergence this fuzz once missed), and \x0b/\x0c are the
    # control-not-whitespace chars BERT drops but Python calls space.
    alphabet = "ab std AB19.,!'-\t\n \x0b\x0c naï中é#"
    rng = random.Random(0)
    for _ in range(200):
        s = "".join(
            rng.choice(alphabet) for _ in range(rng.randrange(0, 40))
        )
        assert bpe.encode(s) == gref.encode(s, add_special_tokens=False), (
            f"BPE fuzz divergence: {s!r}"
        )
        assert bpe.decode(bpe.encode(s)) == s
        assert wp.tokenize(s) == bref.tokenize(s), (
            f"WP fuzz divergence: {s!r}"
        )


# ----------------------------------------------------------- integration
def test_encode_batch_shapes_and_padding():
    tok = _wp()
    ids, mask = encode_batch(tok, ["the model", "a much longer sentence "
                                   "about training models"], max_len=12)
    assert ids.shape == mask.shape == (2, 12)
    assert ids.dtype == mask.dtype == np.int32
    # Row 0 right-padded with [PAD]=0; its mask matches its length.
    n0 = mask[0].sum()
    assert (ids[0, n0:] == tok.pad_id).all()
    # Truncated row keeps the [SEP] terminator.
    assert ids[1, -1] == tok.sep_id or mask[1].sum() < 12


def test_tokenize_texts_prefers_in_tree_over_hash(monkeypatch):
    from ml_trainer_tpu.data import tokenize_texts

    # Discovery picks BPE when both file sets exist (pinned below), so
    # the in-tree path must reproduce the BPE encoding exactly.
    ids, mask = tokenize_texts(
        ["the training model"], max_len=16, vocab_dir=FIX
    )
    ref = _bpe().encode("the training model")
    assert list(ids[0][: len(ref)]) == ref and mask[0].sum() == len(ref)
    # Without vocab files the hash fallback still stands (zero-egress).
    ids2, _ = tokenize_texts(
        ["the training model"], max_len=16, vocab_dir="/nonexistent",
        vocab_size=100,
    )
    assert ids2[0][0] == 1 and ids2.max() < 100  # [CLS]-style framing


def test_load_tokenizer_discovery(tmp_path):
    assert load_tokenizer(str(tmp_path)) is None
    # Both file sets present: BPE wins (vocab.json+merges.txt checked
    # first) — pinned so discovery order is contractual.
    tok = load_tokenizer(FIX)
    assert isinstance(tok, ByteLevelBPETokenizer)


def test_tokenize_texts_guards_embedding_size():
    """An in-tree tokenizer whose vocab exceeds the declared embedding
    size must be SKIPPED with a warning (out-of-range ids would gather
    garbage silently), falling back to the bounded hash tokenizer."""
    from ml_trainer_tpu.data import tokenize_texts

    with pytest.warns(UserWarning, match="vocab_size"):
        ids, _ = tokenize_texts(
            ["the model"], max_len=8, vocab_dir=FIX, vocab_size=100
        )
    assert ids.max() < 100


def test_degenerate_vocab_files_fail_loudly(tmp_path):
    # vocab.json missing byte-alphabet symbols: not byte-level BPE.
    (tmp_path / "vocab.json").write_text('{"a": 0, "b": 1}')
    (tmp_path / "merges.txt").write_text("#version: 0.2\na b\n")
    with pytest.raises(ValueError, match="byte-level"):
        load_tokenizer(str(tmp_path))
    # vocab.txt with [CLS] but no [SEP]: encode must not emit None.
    wp = WordPieceTokenizer({"[CLS]": 0, "the": 1, "[UNK]": 2})
    assert wp.encode("the") == [1]  # unframed, not [0, 1, None]
    # vocab.txt without [UNK]: out-of-vocab words name the gap.
    wp2 = WordPieceTokenizer({"[CLS]": 0, "[SEP]": 1, "the": 2})
    with pytest.raises(ValueError, match="UNK"):
        wp2.encode("zzzz")


def test_pack_texts_builds_lm_dataset():
    from ml_trainer_tpu.data import pack_texts

    ds = pack_texts(
        ["the model trains on the mesh. " * 8] * 4,
        seq_len=16, vocab_dir=FIX, eos_id=0,
    )
    x, y = ds[0]
    assert x.shape == (16,) and y.shape == (16,)
    # Next-token alignment: targets are the stream shifted by one.
    x1, _ = ds[1]
    assert y[-1] == x1[0]
