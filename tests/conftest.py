"""Test harness: simulated 8-device CPU mesh.

The TPU-native analog of the reference's staging story (SURVEY.md §4): where
the reference rehearses SMDDP runs with SageMaker local mode + the gloo
backend, these tests run every distributed path on a virtual 8-device CPU
mesh via ``--xla_force_host_platform_device_count`` — no TPU required, same
compiled collectives.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported by interpreter-startup site hooks with a TPU
# platform pinned; the config override still wins because backends
# initialize lazily on first use.
jax.config.update("jax_platforms", "cpu")

assert jax.default_backend() == "cpu", "tests must run on the simulated CPU mesh"
assert jax.device_count() == 8, "simulated 8-device mesh not active"
