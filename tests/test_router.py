"""Disaggregated prefill/decode serving (serving/router.py, transfer.py).

Ground truth stays ``generate()``: a request whose KV migrates between
replicas at page granularity — prefill on one engine, decode on another,
even a replica DEATH mid-stream with redistribution to a survivor —
must reproduce its standalone batch-1 ``generate()`` output
byte-for-byte, greedy and spec mode alike.  Around that core: the
export/import bit-identity unit (pool -> fresh pool, greedy AND spec_k
continuations), serialization round-trip, affinity placement, session
stickiness, drain-and-redistribute with structured errors past the
redistribution budget, and router metrics on the registry.
"""

import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import Router, Server
from ml_trainer_tpu.serving import transfer
from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.scheduler import Request

PS = 8  # page size used throughout (max_len=64 -> 8 pages per slot)


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


def _drain(engine):
    """Step an engine until every active request finishes."""
    while engine.active_count():
        engine.step()


# ------------------------------------------------------- transfer unit


def test_migration_bit_identity_greedy_mid_stream(model_and_vars):
    """The satellite pin: export a MID-STREAM slot's pages + table from
    one pool, import into a fresh pool, and the greedy continuation is
    byte-identical to the never-migrated run."""
    model, variables = model_and_vars
    p = _prompt(0, 9)
    ref = np.asarray(generate(model, variables, p[None], 20))[0]

    src = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    req = Request(prompt=p, max_new_tokens=20)
    assert src.admit(req, 0) == "active"
    for _ in range(6):
        src.step()
    mid_tokens = list(req.tokens)
    assert 1 < len(mid_tokens) < 20  # genuinely mid-stream
    exp = src.export_slot(0)
    assert exp.n_pages == src.pool.slot_page_count(0)
    assert exp.pos == int(src._pos[0])

    dst = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    cont = Request(prompt=p, max_new_tokens=20)
    cont.tokens = mid_tokens
    assert dst.import_slot(cont, 1, exp) == "active"
    _drain(dst)
    out = np.concatenate([p, np.asarray(cont.tokens, np.int32)])
    np.testing.assert_array_equal(out, ref)
    # The source engine still holds its own copy untouched — export is
    # read-only: finishing the source run stays byte-identical too.
    _drain(src)
    np.testing.assert_array_equal(
        np.concatenate([p, np.asarray(req.tokens, np.int32)]), ref
    )


def test_migration_bit_identity_spec_continuation(model_and_vars):
    """Spec-mode continuation after migration: the verify window reads
    the imported pages and commits byte-identically to generate()."""
    model, variables = model_and_vars
    p = _prompt(1, 11)
    ref = np.asarray(generate(model, variables, p[None], 16))[0]

    src = SlotDecodeEngine(model, variables, max_batch=2,
                           kv_page_size=PS, spec_k=4)
    req = Request(prompt=p, max_new_tokens=16)
    assert src.admit(req, 0) == "active"
    for _ in range(2):
        src.step()
    assert 0 < len(req.tokens) < 16
    exp = src.export_slot(0)

    dst = SlotDecodeEngine(model, variables, max_batch=2,
                           kv_page_size=PS, spec_k=4)
    cont = Request(prompt=p, max_new_tokens=16)
    cont.tokens = list(req.tokens)
    assert dst.import_slot(cont, 0, exp) == "active"
    assert dst._caps[0] == min(p.size + 16 - 1, dst.max_len - 4 - 1)
    _drain(dst)
    out = np.concatenate([p, np.asarray(cont.tokens, np.int32)])
    np.testing.assert_array_equal(out, ref)


def test_transfer_serialization_round_trip(model_and_vars):
    """to_bytes/from_bytes is lossless — the payload is transport-ready
    and the byte count the router meters is the real moved volume."""
    model, variables = model_and_vars
    p = _prompt(2, 10)
    eng = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    req = Request(prompt=p, max_new_tokens=8, temperature=0.7, rng=42)
    eng.admit(req, 0)
    exp = eng.export_slot(0)
    payload = transfer.to_bytes(exp)
    assert len(payload) >= exp.nbytes()
    back = transfer.from_bytes(payload)
    for field in ("page_size", "pages_per_slot", "max_len", "n_pages",
                  "pos", "tokens", "last_token", "step_counter"):
        assert getattr(back, field) == getattr(exp, field), field
    assert back.temperature == pytest.approx(exp.temperature)
    np.testing.assert_array_equal(back.prompt, exp.prompt)
    np.testing.assert_array_equal(back.rng_key, exp.rng_key)
    assert len(back.layers) == len(exp.layers)
    for a, b in zip(back.layers, exp.layers):
        np.testing.assert_array_equal(a, b)


def test_import_geometry_mismatch_is_structured(model_and_vars):
    model, variables = model_and_vars
    eng = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    req = Request(prompt=_prompt(3, 9), max_new_tokens=4)
    eng.admit(req, 0)
    exp = eng.export_slot(0)
    other = SlotDecodeEngine(model, variables, max_batch=2,
                             kv_page_size=16)
    cont = Request(prompt=exp.prompt, max_new_tokens=4)
    with pytest.raises(ValueError, match="geometry"):
        other.import_slot(cont, 0, exp)
    contig = SlotDecodeEngine(model, variables, max_batch=2)
    with pytest.raises(ValueError, match="paged"):
        contig.import_slot(cont, 0, exp)


def test_import_no_memory_reports_instead_of_wedging(model_and_vars):
    """A target pool too small for the chain returns "no_memory" (the
    server falls back to requeue-and-reprefill) without corrupting the
    pool: nothing stays bound."""
    model, variables = model_and_vars
    src = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    req = Request(prompt=_prompt(4, 30), max_new_tokens=4)
    src.admit(req, 0)
    exp = src.export_slot(0)
    dst = SlotDecodeEngine(model, variables, max_batch=2,
                           kv_page_size=PS, kv_pages=exp.n_pages,
                           prefix_cache=False)  # 1 allocatable short
    cont = Request(prompt=exp.prompt, max_new_tokens=4)
    assert dst.import_slot(cont, 0, exp) == "no_memory"
    assert dst.pool.slot_page_count(0) == 0
    assert dst.active_count() == 0


# ----------------------------------------------------- router end to end


def test_router_disagg_byte_identity_greedy_and_sampled(model_and_vars):
    """Requests routed prefill -> migrate -> decode reproduce their
    standalone generate() outputs, greedy and seeded sampling alike,
    and migrations actually happened."""
    model, variables = model_and_vars
    pA, pB, pC = _prompt(5, 9), _prompt(6, 5), _prompt(7, 12)
    refA = np.asarray(generate(model, variables, pA[None], 16))[0]
    refB = np.asarray(generate(model, variables, pB[None], 10))[0]
    refC = np.asarray(
        generate(model, variables, pC[None], 10, temperature=0.7,
                 rng=jax.random.PRNGKey(42))
    )[0]
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        sA = router.submit(pA, 16)
        sB = router.submit(pB, 10)
        sC = router.submit(pC, 10, temperature=0.7, rng=42)
        outs = [s.result(timeout=180) for s in (sA, sB, sC)]
        snap = router.snapshot()
    np.testing.assert_array_equal(outs[0], refA)
    np.testing.assert_array_equal(outs[1], refB)
    np.testing.assert_array_equal(outs[2], refC)
    assert snap["migrations_total"] >= 3
    assert snap["kv_migrated_bytes_total"] > 0
    assert snap["mode"] == "disagg"


def test_router_colocated_matches_disagg(model_and_vars):
    """Colocated mode (every replica both roles, no migration) serves
    the same trace byte-identically — the equal-replica-count
    comparison bench.py --serve-disagg runs."""
    model, variables = model_and_vars
    prompts = [_prompt(s, 6 + s % 5) for s in (8, 9, 10)]
    refs = [
        np.asarray(generate(model, variables, p[None], 8))[0]
        for p in prompts
    ]
    with Router.build(model, variables, roles=["both", "both"],
                      max_batch=2, kv_page_size=PS) as router:
        outs = [router.complete(p, 8, timeout=180) for p in prompts]
        snap = router.snapshot()
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    assert snap["mode"] == "colocated"
    assert snap["migrations_total"] == 0


def test_affinity_routes_same_prefix_to_same_prefill_replica(
        model_and_vars):
    """Consistent hashing on tenant + first KV block: requests sharing
    a system prompt land on ONE prefill replica (its prefix cache keeps
    the hit rate), different prefixes may spread."""
    model, variables = model_and_vars
    shared = _prompt(11, PS)  # one full block, the affinity key
    with Router.build(model, variables,
                      roles=["prefill", "prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        suffixes = [_prompt(100 + i, 4) for i in range(4)]
        for sfx in suffixes:
            router.complete(
                np.concatenate([shared, sfx]), 2, timeout=180,
                tenant="affine",
            )
        snap = router.snapshot()
        hits = router.replica("prefill0").server.engine._prefix.hits \
            + router.replica("prefill1").server.engine._prefix.hits
    placed = {
        key: n for key, n in snap["requests_total"].items()
        if key.startswith("prefill/")
    }
    # All four identical-prefix requests prefilled on the same replica...
    assert len(placed) == 1 and sum(placed.values()) == 4, placed
    # ...so after the first, every one hit that replica's prefix cache.
    assert hits >= 3


def test_session_stickiness_pins_decode_replica(model_and_vars):
    model, variables = model_and_vars
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        for i in range(3):
            router.complete(_prompt(20 + i, 6), 3, timeout=180,
                            session="chat-1")
        snap = router.snapshot()
    decode_placed = {
        key: n for key, n in snap["requests_total"].items()
        if key.startswith("decode/")
    }
    assert len(decode_placed) == 1 and sum(decode_placed.values()) == 3, \
        decode_placed
    assert snap["sessions"] == 1


def test_replica_kill_redistributes_in_flight(model_and_vars):
    """The acceptance pin: a decode replica dies MID-STREAM; the router
    redistributes its in-flight requests to a survivor, the job
    completes, and every output stays byte-identical."""
    model, variables = model_and_vars
    prompts = [_prompt(30 + i, 7 + i) for i in range(4)]
    refs = [
        np.asarray(generate(model, variables, p[None], 28))[0]
        for p in prompts
    ]
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        streams = [router.submit(p, 28) for p in prompts]
        deadline = time.monotonic() + 120
        while any(len(s.tokens) < 2 for s in streams):
            assert time.monotonic() < deadline, "streams never started"
            time.sleep(0.02)
        router.kill_replica("decode0")
        outs = [np.asarray(s.result(timeout=180)) for s in streams]
        snap = router.snapshot()
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    assert snap["redistributes_total"] >= 1
    assert snap["replica_healthy"]["decode0"] == 0
    assert snap["replica_healthy"]["decode1"] == 1


def test_redistribution_budget_exhaustion_is_structured(model_and_vars):
    """Past the redistribution budget the client gets a STRUCTURED
    error naming the request, the budget and the root cause — never a
    hang."""
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS,
                      router_kwargs={"max_redistributes": 0,
                                     "admission_retry_s": 2.0},
                      ) as router:
        s = router.submit(_prompt(40, 8), 40)
        deadline = time.monotonic() + 120
        while len(s.tokens) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        router.kill_replica("decode0")
        with pytest.raises(RuntimeError, match="max_redistributes"):
            s.result(timeout=180)


def test_router_metrics_on_registry(model_and_vars):
    """router_* series land on the registry with their labels — what
    the smoke leg's /metrics scrape asserts over HTTP."""
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry

    model, variables = model_and_vars
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        router.complete(_prompt(50, 6), 4, timeout=180)
        reg = MetricsRegistry()
        router.publish(reg)
        text = reg.prometheus_text()
    assert 'router_requests_total{replica="prefill0",role="prefill"}' \
        in text or \
        'router_requests_total{role="prefill",replica="prefill0"}' in text
    assert "router_kv_migrated_bytes_total" in text
    assert 'router_replica_healthy{replica="decode0"} 1' in text
    assert 'router_replica_slo_attainment{' in text
    assert "router_redistributes_total" in text


def test_router_rejects_heterogeneous_or_contiguous_fleet(model_and_vars):
    model, variables = model_and_vars
    srv_paged = Server(model, variables, max_batch=2, kv_page_size=PS,
                       role="prefill")
    srv_contig = Server(model, variables, max_batch=2, role="decode")
    try:
        with pytest.raises(ValueError, match="paged"):
            Router({"p0": srv_paged, "d0": srv_contig})
        with pytest.raises(ValueError, match="role"):
            Server(model, variables, max_batch=2, role="router")
    finally:
        srv_paged.close()
        srv_contig.close()


def test_router_validates_requests(model_and_vars):
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["both"],
                      max_batch=2, kv_page_size=PS) as router:
        with pytest.raises(ValueError, match="non-empty"):
            router.submit(np.asarray([], np.int32), 4)
        with pytest.raises(ValueError, match="max_len"):
            router.submit(_prompt(60, 8), 1000)
        with pytest.raises(ValueError, match="eos_token_id"):
            router.submit(_prompt(60, 8), 4, eos_token_id=10**6)
