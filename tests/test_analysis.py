"""graft-lint: seeded known-bad fixtures + zero-false-positive pins.

Two halves, mirroring the subsystem:

* every checker FIRES on a fixture built to violate its contract
  (mismatched ppermute across switch branches, fp32 matmul under the
  bf16 policy, an undonated aliasable buffer, a lock cycle, ``.item()``
  in a registered hot loop, an unused import);
* every checker stays SILENT on the real tree — the AST pack over the
  actual sources and the jaxpr checks over the actual traced
  train/decode/pipeline programs report zero findings, pinned
  non-vacuously (the traced programs demonstrably contain the
  constructs the checkers inspect).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ml_trainer_tpu.analysis import (
    Report,
    baseline_payload,
    check_collective_uniformity,
    check_dtype_policy,
    check_program,
    check_traceable,
    diff_against_baseline,
    modules_from_sources,
    run_ast_checks,
    scan_tree,
)
from ml_trainer_tpu.analysis import ast_checks, jaxpr_checks
from ml_trainer_tpu.analysis.findings import Finding
from ml_trainer_tpu.parallel.compat import shard_map

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("data",))


# ------------------------------------------------- collective uniformity
class TestCollectiveUniformity:
    def _switch_program(self, matched: bool):
        mesh = _mesh2()

        def body(x):
            def b0(v):
                return lax.ppermute(v, "data", [(0, 1), (1, 0)])

            def b1(v):
                perm = [(0, 1), (1, 0)] if matched else [(0, 1)]
                return lax.ppermute(v, "data", perm) * 2.0

            return lax.switch((x.sum() > 0).astype(jnp.int32), (b0, b1), x)

        f = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        ))
        return f.trace(jnp.ones((4, 2)))

    def test_mismatched_ppermute_across_branches_fires(self):
        traced = self._switch_program(matched=False)
        out = check_collective_uniformity(traced.jaxpr, "fixture")
        assert len(out) == 1
        assert out[0].rule == "collective-mismatch"
        assert out[0].severity == "error"
        # The finding carries both branches' wire programs.
        branches = out[0].details["branch_collectives"]
        assert len(branches) == 2 and branches[0] != branches[1]

    def test_matched_branches_pass(self):
        traced = self._switch_program(matched=True)
        assert check_collective_uniformity(traced.jaxpr, "fixture") == []

    def test_op_kind_mismatch_fires(self):
        mesh = _mesh2()

        def body(x):
            return lax.switch(
                (x.sum() > 0).astype(jnp.int32),
                (lambda v: lax.psum(v, "data"),
                 lambda v: lax.ppermute(v, "data", [(0, 1), (1, 0)])),
                x,
            )

        f = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        ))
        out = check_collective_uniformity(f.trace(jnp.ones((4,))).jaxpr,
                                          "fixture")
        assert [f_.rule for f_ in out] == ["collective-mismatch"]


# ------------------------------------------------------ dtype policy
class TestDtypePolicy:
    def test_fp32_matmul_under_bf16_fires(self):
        def f(a, b):
            return (a @ b).sum()

        traced = jax.jit(f).trace(
            jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32)
        )
        out = check_dtype_policy(traced.jaxpr, "fixture", "bf16")
        assert [x.rule for x in out] == ["fp32-compute-under-bf16"]
        assert out[0].details["primitive"] == "dot_general"

    def test_bf16_matmul_passes_and_fp32_policy_exempt(self):
        def f(a, b):
            return (a @ b).sum()

        bf = jax.jit(f).trace(
            jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8), jnp.bfloat16)
        )
        assert check_dtype_policy(bf.jaxpr, "fixture", "bf16") == []
        fp = jax.jit(f).trace(
            jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32)
        )
        assert check_dtype_policy(fp.jaxpr, "fixture", "fp32") == []

    def test_bf16_gradient_psum_fires(self):
        mesh = _mesh2()

        def body(g):
            return lax.psum(g, "data")

        f = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P(),
            check_vma=False,
        ))
        traced = f.trace(jnp.ones((4, 4), jnp.bfloat16))
        out = check_dtype_policy(traced.jaxpr, "fixture", "bf16")
        assert "bf16-gradient-reduction" in [x.rule for x in out]


# ---------------------------------------------------- donation auditing
class TestDonationAudit:
    def _step(self):
        def step(state, x):
            return {"w": state["w"] + x.sum()}, x.mean()

        args = ({"w": jnp.ones((256, 256))}, jnp.ones((4, 4)))
        return step, args

    def test_undonated_aliasable_buffer_fires_with_priced_bytes(self):
        step, args = self._step()
        traced = jax.jit(step).trace(*args)
        out = jaxpr_checks.audit_donation(traced, "fixture",
                                          min_bytes=1 << 10)
        assert [f.rule for f in out] == ["undonated-buffer"]
        # Priced through the memory ledger: 256*256*4 bytes.
        assert out[0].details["undonated_bytes"] == 256 * 256 * 4

    def test_donated_step_passes_and_aliasing_verified(self):
        step, args = self._step()
        traced = jax.jit(step, donate_argnums=0).trace(*args)
        lowered = traced.lower().as_text()
        assert jaxpr_checks.audit_donation(
            traced, "fixture", min_bytes=1 << 10, lowered_text=lowered
        ) == []

    def test_small_buffers_below_threshold_ignored(self):
        step, args = self._step()
        traced = jax.jit(step).trace(*args)
        assert jaxpr_checks.audit_donation(
            traced, "fixture", min_bytes=1 << 20
        ) == []


# ------------------------------------------------------ host-sync probe
class TestHostSyncProbe:
    def test_item_in_step_fn_becomes_finding(self):
        def bad_step(x):
            return x * float(jnp.sum(x))  # forces the tracer to host

        out = check_traceable(
            lambda: jax.jit(bad_step).trace(jnp.ones((4,))), "bad_step"
        )
        assert [f.rule for f in out] == ["host-sync-in-program"]

    def test_clean_step_traces(self):
        assert check_traceable(
            lambda: jax.jit(lambda x: x * 2).trace(jnp.ones((4,))), "ok"
        ) == []


# ---------------------------------------------------------- lock order
_LOCK_CYCLE_SRC = {
    "pkg/a.py": """
import threading

class Engine:
    def __init__(self, cache: "Cache"):
        self._lock = threading.Lock()
        self._cache = cache
        self.jobs = 0

    def run(self):
        with self._lock:
            self.jobs += 1
            self._cache.get()
""",
    "pkg/b.py": """
import threading

class Cache:
    def __init__(self, engine: "Engine"):
        self._lock = threading.Lock()
        self._engine = engine

    def get(self):
        with self._lock:
            return 1

    def evict(self):
        with self._lock:
            self._engine.run()
""",
}


class TestLockOrder:
    def test_cycle_between_engine_and_cache_fires(self):
        modules = modules_from_sources(_LOCK_CYCLE_SRC)
        out = ast_checks.check_lock_order(modules)
        cycles = [f for f in out if f.rule == "lock-order-cycle"]
        # The A<->B inversion proper (evict holds Cache._lock and calls
        # into Engine.run which takes Engine._lock; run holds
        # Engine._lock and calls into Cache.get which takes
        # Cache._lock)...
        assert any(
            set(c.details["cycle"]) == {"Engine._lock", "Cache._lock"}
            for c in cycles
        )
        # ...and the transitive self-reacquisition evict->run->get also
        # latent in the fixture — both are genuine deadlocks.
        assert all(f.severity == "error" for f in cycles)

    def test_self_reacquire_plain_lock_fires_rlock_passes(self):
        src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.{kind}()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def bump_twice(self):
        with self._lock:
            self.bump()
"""
        bad = modules_from_sources({"m.py": src.format(kind="Lock")})
        out = ast_checks.check_lock_order(bad)
        assert any(
            f.rule == "lock-order-cycle" and len(f.details["cycle"]) == 2
            for f in out
        )
        ok = modules_from_sources({"m.py": src.format(kind="RLock")})
        assert ast_checks.check_lock_order(ok) == []

    def test_ordered_nesting_passes(self):
        src = """
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self._b = B()

    def run(self):
        with self._lock:
            self._b.get()

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def get(self):
        with self._lock:
            return 1
"""
        modules = modules_from_sources({"m.py": src})
        assert ast_checks.check_lock_order(modules) == []


# ------------------------------------------------- unguarded shared state
class TestSharedState:
    _SRC = """
import threading

class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def record(self):
        with self._lock:
            self.count += 1

    def reset(self):
        self.count = 0
"""

    def test_unguarded_mutation_fires(self):
        out = ast_checks.check_shared_state(
            modules_from_sources({"m.py": self._SRC})
        )
        assert [f.rule for f in out] == ["unguarded-shared-state"]
        assert out[0].details["attr"] == "count"

    def test_private_helper_called_under_lock_passes(self):
        src = self._SRC.replace("def reset(self):", "def _reset(self):") \
            .replace("self.count += 1", "self.count += 1\n            self._reset()")
        assert ast_checks.check_shared_state(
            modules_from_sources({"m.py": src})
        ) == []

    def test_caller_holds_the_lock_comment_honored(self):
        src = self._SRC.replace(
            "    def reset(self):",
            "    def reset(self):\n        # Caller holds the lock.",
        )
        assert ast_checks.check_shared_state(
            modules_from_sources({"m.py": src})
        ) == []


# --------------------------------------------------- host-only + hot-loop
class TestHostRules:
    def test_jax_import_in_scheduler_fires(self):
        out = ast_checks.check_host_only_modules(modules_from_sources({
            "ml_trainer_tpu/serving/scheduler.py":
                "import jax\nimport numpy as np\n",
        }))
        assert [f.rule for f in out] == ["device-op-in-host-module"]

    def test_item_in_hot_loop_fires_and_sync_ok_suppresses(self):
        body = """
import numpy as np

class SlotDecodeEngine:
    def step(self):
        toks = self.tok.item(){suffix}
        return toks
"""
        fires = ast_checks.check_host_sync(modules_from_sources({
            "ml_trainer_tpu/serving/engine.py": body.format(suffix=""),
        }))
        assert [f.rule for f in fires] == ["host-sync-hot-loop"]
        quiet = ast_checks.check_host_sync(modules_from_sources({
            "ml_trainer_tpu/serving/engine.py":
                body.format(suffix="  # graft-lint: sync-ok"),
        }))
        assert quiet == []

    def test_cold_functions_not_scanned(self):
        out = ast_checks.check_host_sync(modules_from_sources({
            "ml_trainer_tpu/serving/engine.py": (
                "class SlotDecodeEngine:\n"
                "    def admit(self):\n"
                "        return self.tok.item()\n"
            ),
        }))
        assert out == []


# ------------------------------------------------------- import hygiene
class TestImportHygiene:
    def test_unused_import_fires_noqa_and_init_exempt(self):
        out = ast_checks.check_unused_imports(modules_from_sources({
            "m.py": "import os\nimport json\nprint(json.dumps({}))\n",
        }))
        assert [f.rule for f in out] == ["unused-import"]
        assert "os" in out[0].message
        assert ast_checks.check_unused_imports(modules_from_sources({
            "m.py": "import os  # noqa\n",
        })) == []
        assert ast_checks.check_unused_imports(modules_from_sources({
            "pkg/__init__.py": "from pkg.sub import thing\n",
        })) == []

    def test_all_reexport_counts_as_use(self):
        assert ast_checks.check_unused_imports(modules_from_sources({
            "m.py": "from x import y\n__all__ = [\"y\"]\n",
        })) == []


# -------------------------------------------------------- baseline logic
class TestBaseline:
    def test_new_finding_fails_fixed_finding_reported(self):
        f1 = Finding("unused-import", "warn", "a.py:3", "'os' unused")
        f2 = Finding("lock-order-cycle", "error", "b.py:9", "cycle A-B")
        baseline = baseline_payload(Report([f1]))
        # Same findings -> ok; line drift does not break the key.
        moved = Finding("unused-import", "warn", "a.py:99", "'os' unused")
        assert diff_against_baseline(Report([moved]), baseline)["ok"]
        # A new rule violation -> fail, naming only the new one.
        d = diff_against_baseline(Report([moved, f2]), baseline)
        assert not d["ok"] and len(d["new"]) == 1
        assert d["new"][0]["rule"] == "lock-order-cycle"
        # A fixed finding is informational.
        d2 = diff_against_baseline(Report([]), baseline)
        assert d2["ok"] and len(d2["fixed"]) == 1
        # No baseline: everything is new.
        assert not diff_against_baseline(Report([moved]), None)["ok"]


# -------------------------------------------------- real-tree pins (0 FP)
class TestRealTreeClean:
    def test_ast_pack_zero_findings_on_real_tree(self):
        modules = scan_tree(REPO)
        assert len(modules) > 80  # the real tree, not an empty walk
        report = run_ast_checks(modules)
        assert report == [], Report(report).render()

    def test_fixed_modules_stay_import_clean(self):
        # Regression for the unused-import sweep this PR landed
        # (loader/bert/vit/collectives/ring/faults/scheduler/
        # compile_watch/memory).
        fixed = [
            "ml_trainer_tpu/data/loader.py",
            "ml_trainer_tpu/models/bert.py",
            "ml_trainer_tpu/models/vit.py",
            "ml_trainer_tpu/parallel/collectives.py",
            "ml_trainer_tpu/parallel/ring.py",
            "ml_trainer_tpu/resilience/faults.py",
            "ml_trainer_tpu/serving/scheduler.py",
            "ml_trainer_tpu/telemetry/compile_watch.py",
            "ml_trainer_tpu/telemetry/memory.py",
        ]
        modules = scan_tree(REPO, subdirs=("ml_trainer_tpu",))
        subset = {k: v for k, v in modules.items() if k in fixed}
        assert len(subset) == len(fixed)
        assert ast_checks.check_unused_imports(subset) == []

    def test_hot_loop_fences_stay_annotated(self):
        # Regression for the sync-point annotation sweep: every
        # intentional fence in the engine step loops and trainer epoch
        # loops carries its graft-lint annotation.
        modules = scan_tree(REPO, subdirs=("ml_trainer_tpu",))
        assert ast_checks.check_host_sync(modules) == []

    def test_host_modules_stay_device_free(self):
        modules = scan_tree(REPO, subdirs=("ml_trainer_tpu",))
        assert ast_checks.check_host_only_modules(modules) == []


class TestRealProgramsClean:
    def test_decode_programs_zero_findings_and_nonvacuous(self):
        from ml_trainer_tpu.analysis import programs as PR

        specs = PR.build_decode_specs(paged=True, spec_k=2)
        assert {s.name for s in specs} >= {
            "serve_decode[contiguous]", "serve_decode[paged]",
            "spec_verify[k2]",
        }
        all_findings = []
        donated_programs = 0
        for s in specs:
            all_findings += check_program(
                s.traced, s.name, policy=s.policy,
                min_donation_bytes=s.min_donation_bytes,
            )
            flat = jax.tree_util.tree_flatten_with_path(
                s.traced.args_info
            )[0]
            if any(getattr(i, "donated", False) for _, i in flat):
                donated_programs += 1
        assert all_findings == [], Report(all_findings).render()
        # Non-vacuous: the decode/insert family really does donate.
        assert donated_programs >= 3

    def test_train_programs_zero_findings_and_bf16_policy_holds(self):
        from ml_trainer_tpu.analysis import programs as PR

        specs = PR.build_train_specs()
        assert any("sharded" in s.name for s in specs)
        all_findings = []
        bf16_dots = 0
        sharded_reductions = 0
        for s in specs:
            all_findings += check_program(
                s.traced, s.name, policy=s.policy,
                min_donation_bytes=s.min_donation_bytes,
            )
            if s.policy == "bf16":
                for e in jaxpr_checks.iter_eqns(s.traced.jaxpr):
                    if e.primitive.name == "dot_general":
                        bf16_dots += 1
                    if "sharded" in s.name and e.primitive.name in (
                        "reduce_scatter", "all_gather", "psum"
                    ):
                        sharded_reductions += 1
        assert all_findings == [], Report(all_findings).render()
        # Non-vacuous: the bf16 programs carry real matmuls the dtype
        # rule inspected, and the sharded-dp step carries the bucketed
        # reduce-scatter/all-gather the reduction rule inspected (all
        # fp32 per the PR7 contract — a bf16 one would have fired).
        assert bf16_dots > 0
        assert sharded_reductions >= 3

    def test_pipeline_program_zero_findings_and_nonvacuous(self):
        from ml_trainer_tpu.analysis import programs as PR

        specs = PR.build_pipeline_specs()
        assert specs, "stage mesh unavailable on the 8-device harness?"
        s = specs[0]
        conds = sum(
            1 for e in jaxpr_checks.iter_eqns(s.traced.jaxpr)
            if e.primitive.name == "cond"
        )
        colls = sum(
            1 for e in jaxpr_checks.iter_eqns(s.traced.jaxpr)
            if e.primitive.name in jaxpr_checks.COLLECTIVE_PRIMS
        )
        # The tick-table engine is the switch+ppermute composition the
        # collective checker exists for.
        assert conds >= 2 and colls >= 2
        out = check_program(s.traced, s.name, policy=s.policy,
                            min_donation_bytes=s.min_donation_bytes)
        assert out == [], Report(out).render()


# ------------------------------------------------- flight-context provider
class TestFlightContext:
    def test_baseline_fingerprint_rides_flight_dumps(self, tmp_path):
        import json

        from ml_trainer_tpu.analysis import (
            default_baseline_path,
            register_flight_context,
        )
        from ml_trainer_tpu.telemetry.flight import FlightRecorder

        rec = FlightRecorder(capacity=4, default_dir=str(tmp_path))
        register_flight_context(rec)
        rec.record("step", n=1)
        path = rec.dump("test")
        payload = json.load(open(path))
        ctx = payload["context"]["lint_baseline"]
        committed = json.load(open(default_baseline_path()))
        assert ctx["present"] is True
        assert ctx["fingerprint"] == committed["fingerprint"]


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
