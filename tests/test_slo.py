"""Request-lifecycle tracing, SLO attainment telemetry, and the
open-loop load harness (serving/slo.py, serving/loadgen.py).

Ground truths pinned here: the lifecycle latency histograms expose
exact Prometheus ``_bucket``/``_sum``/``_count`` semantics and never
double-count across publishes; the SLO arithmetic (attainment, burn
rate) matches hand-computed values on synthetic timelines; a seeded
load schedule is byte-reproducible (the property that makes sweeps
comparable); steady-state open-loop traffic mints ZERO compiles
(compile_watch-pinned); and a forced preemption's flight dump names the
hurt request ids with their timelines attached (the forensics
acceptance criterion)."""

import threading
import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import (
    Server,
    SloPolicy,
    SloTracker,
    TenantLoad,
    poisson_schedule,
    run_open_loop,
    schedule_from_trace,
)
from ml_trainer_tpu.serving.loadgen import schedule_to_records
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.scheduler import Request
from ml_trainer_tpu.serving.slo import aggregate_timelines
from ml_trainer_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _finished_request(tenant="default", ttft_s=0.01, tpot_s=0.005,
                      n_tokens=4, state="done"):
    """A synthetic finished Request with a fabricated timeline: known
    queue wait (1ms), TTFT and inter-token gaps, so the SLO arithmetic
    is checkable by hand."""
    req = Request(prompt=np.asarray([1, 2, 3], np.int32),
                  max_new_tokens=n_tokens, tenant=tenant)
    t0 = req.submitted_at
    req.first_admitted_at = t0 + 1e-3
    req.admitted_at = req.first_admitted_at
    req.prefill_secs = max(ttft_s - 1e-3, 0.0)
    req.token_times = [
        t0 + ttft_s + i * tpot_s for i in range(n_tokens)
    ]
    req.first_token_at = req.token_times[0]
    req.tokens = list(range(n_tokens))
    req.state = state
    req.finished_at = req.token_times[-1]
    return req


def test_slo_policy_validation():
    with pytest.raises(ValueError, match="positive"):
        SloPolicy(ttft_ms=0)
    with pytest.raises(ValueError, match="target"):
        SloPolicy(target=1.0)
    with pytest.raises(ValueError, match="keep_timelines"):
        SloTracker(keep_timelines=0)


def test_latency_histogram_golden_exposition():
    """The promoted TTFT/TPOT histograms expose exact cumulative
    ``le`` buckets + ``_sum``/``_count``, and a second publish never
    double-counts (the delta-observed pattern)."""
    m = ServingMetrics()
    for v in (0.0005, 0.003, 0.003, 0.2):
        m.record_ttft(v, tenant="t0")
    m.record_tpot([0.004, 0.09], tenant="t0")
    reg = MetricsRegistry()
    m.publish(reg)
    first = reg.prometheus_text()
    # Cumulative buckets: 0.0005 -> le=0.001 holds 1; the two 3ms
    # observations land at le=0.005 (cumulative 3); 0.2 at le=0.25
    # (cumulative 4 from there up).
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="0.001"} 1' in first
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="0.0025"} 1' in first
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="0.005"} 3' in first
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="0.1"} 3' in first
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="0.25"} 4' in first
    assert 'serving_ttft_seconds_bucket{tenant="t0",le="+Inf"} 4' in first
    assert 'serving_ttft_seconds_sum{tenant="t0"} 0.2065' in first
    assert 'serving_ttft_seconds_count{tenant="t0"} 4' in first
    assert 'serving_tpot_seconds_bucket{tenant="t0",le="0.005"} 1' in first
    assert 'serving_tpot_seconds_count{tenant="t0"} 2' in first
    # Publish again with no new observations: identical exposition.
    m.publish(reg)
    assert reg.prometheus_text() == first
    # New observation after the second publish: count moves by one.
    m.record_ttft(0.0005, tenant="t0")
    m.publish(reg)
    assert 'serving_ttft_seconds_count{tenant="t0"} 5' \
        in reg.prometheus_text()


def test_attainment_and_burn_rate_arithmetic():
    """3 of 4 requests meet TTFT, all meet TPOT, target 0.9 =>
    attainment 0.75 / burn 2.5 on ttft, 1.0 / 0.0 on tpot; a failed
    request misses both SLOs by definition."""
    tracker = SloTracker(policy=SloPolicy(ttft_ms=50.0, tpot_ms=20.0,
                                          target=0.9))
    for _ in range(3):
        tracker.observe(_finished_request(ttft_s=0.01))
    tracker.observe(_finished_request(ttft_s=0.5))  # misses TTFT
    snap = tracker.snapshot()
    assert snap["requests_observed"] == 4
    assert snap["attainment"] == {"ttft": 0.75, "tpot": 1.0}
    assert snap["burn_rate"]["ttft"] == pytest.approx(2.5)
    assert snap["burn_rate"]["tpot"] == 0.0
    tracker.observe(_finished_request(ttft_s=0.01, state="error"))
    snap = tracker.snapshot()
    assert snap["requests_failed"] == 1
    assert snap["attainment"]["ttft"] == 0.6  # 3 of 5
    assert snap["attainment"]["tpot"] == 0.8  # failed request misses
    # aggregate_timelines (the harness's window-scoped view) agrees.
    agg = aggregate_timelines(tracker.timelines(), tracker.policy)
    assert agg["attainment"] == snap["attainment"]
    assert agg["n_failed"] == 1
    # Publish: per-tenant + aggregate series land in the registry.
    reg = MetricsRegistry()
    tracker.publish(reg)
    text = reg.prometheus_text()
    assert 'serving_slo_attainment{slo="ttft",tenant="all"} 0.6' in text
    assert 'serving_slo_burn_rate{slo="ttft",tenant="default"}' in text
    assert 'serving_slo_target_ms{slo="tpot"} 20' in text


def test_timeline_decomposes_ttft():
    """queue_wait + prefill ~= ttft on the synthetic timeline, and the
    tpot stats match the fabricated gaps."""
    req = _finished_request(ttft_s=0.02, tpot_s=0.004, n_tokens=5)
    tl = req.timeline()
    assert tl["queue_wait_ms"] == pytest.approx(1.0, abs=1e-6)
    assert tl["prefill_ms"] == pytest.approx(19.0, abs=1e-6)
    assert tl["ttft_ms"] == pytest.approx(20.0, abs=1e-3)
    assert tl["queue_wait_ms"] + tl["prefill_ms"] == pytest.approx(
        tl["ttft_ms"], abs=1e-3
    )
    assert tl["tpot_ms"]["mean"] == pytest.approx(4.0, abs=1e-3)
    assert tl["tpot_ms"]["p50"] == pytest.approx(4.0, abs=1e-3)
    assert tl["new_tokens"] == 5


def test_tracker_concurrent_observe_vs_snapshot_hammer():
    """The SLO accounting's concurrency contract: observe() from many
    threads while snapshot()/publish()/context_payload() scrape — no
    crashes, and the final count equals the observations made."""
    tracker = SloTracker(policy=SloPolicy(ttft_ms=50.0, tpot_ms=20.0))
    stop = threading.Event()
    errors, observed = [], []

    def producer(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                req = _finished_request(
                    tenant=f"t{seed}", ttft_s=float(rng.random() * 0.1)
                )
                tracker.track(req)
                tracker.observe(req)
                observed.append(1)
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    def scraper():
        reg = MetricsRegistry()
        try:
            while not stop.is_set():
                snap = tracker.snapshot()
                assert 0.0 <= snap["attainment"]["ttft"] <= 1.0
                tracker.publish(reg)
                tracker.context_payload()
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    threads = [threading.Thread(target=producer, args=(i,))
               for i in range(3)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert tracker.snapshot()["requests_observed"] == len(observed)


def test_loadgen_schedule_deterministic():
    """Same seed => byte-identical schedule (arrivals, tenants, prompts,
    budgets); a different seed differs; shared prefixes are applied."""
    mix = {
        "pro": TenantLoad(weight=2.0, shared_prefix_len=8,
                          shared_frac=1.0),
        "free": TenantLoad(),
    }
    a = poisson_schedule(50.0, 24, 1024, tenants=mix, seed=7)
    b = poisson_schedule(50.0, 24, 1024, tenants=mix, seed=7)
    c = poisson_schedule(50.0, 24, 1024, tenants=mix, seed=8)
    assert len(a) == len(b) == 24
    for x, y in zip(a, b):
        assert x.arrival_s == y.arrival_s
        assert x.tenant == y.tenant
        assert x.max_new_tokens == y.max_new_tokens
        np.testing.assert_array_equal(x.prompt, y.prompt)
    assert any(
        x.arrival_s != y.arrival_s
        or not np.array_equal(x.prompt, y.prompt)
        for x, y in zip(a, c)
    )
    # Arrivals are sorted (a fixed open-loop schedule) and every "pro"
    # prompt opens with the tenant's shared prefix.
    assert all(
        a[i].arrival_s <= a[i + 1].arrival_s for i in range(len(a) - 1)
    )
    pro = [s for s in a if s.tenant == "pro"]
    assert pro, "weighted mix produced no pro arrivals"
    head = pro[0].prompt[:8]
    assert all(np.array_equal(s.prompt[:8], head) for s in pro)
    with pytest.raises(ValueError, match="rate_rps"):
        poisson_schedule(0.0, 4, 1024)


def test_loadgen_trace_round_trip(tmp_path):
    sched = poisson_schedule(20.0, 6, 512, seed=3)
    records = schedule_to_records(sched)
    path = tmp_path / "trace.json"
    import json

    path.write_text(json.dumps(records))
    back = schedule_from_trace(str(path))
    assert len(back) == len(sched)
    for x, y in zip(sched, back):
        assert x.arrival_s == pytest.approx(y.arrival_s, abs=1e-6)
        assert (x.tenant, x.max_new_tokens) == (y.tenant, y.max_new_tokens)
        np.testing.assert_array_equal(x.prompt, y.prompt)


def test_open_loop_populates_slo_accounting(model_and_vars):
    """A small in-process open-loop run: every request completes, the
    tracker observed each, the snapshot carries the TTFT decomposition
    fields (with the legacy keys intact), and attainment is computed."""
    model, variables = model_and_vars
    sched = poisson_schedule(
        40.0, 6, model.vocab_size,
        tenants={"default": TenantLoad(prompt_len=(5, 9),
                                       output_len=(2, 4))},
        seed=1,
    )
    with Server(model, variables, max_batch=2, max_queue=16,
                slo=SloPolicy(ttft_ms=60_000, tpot_ms=60_000)) as srv:
        report = run_open_loop(sched, server=srv, timeout=300)
        snap = srv.metrics.snapshot()
        slo = srv.slo.snapshot()
    assert report["n_completed"] == 6 and report["n_errors"] == 0
    assert report["tokens_per_sec"] > 0
    assert slo["requests_observed"] == 6
    assert slo["attainment"] == {"ttft": 1.0, "tpot": 1.0}
    # TTFT decomposition + new percentile fields, legacy shape intact.
    for key in ("ttft_p50_ms", "prefill_p50_ms", "queue_wait_p50_ms",
                "queue_wait_p99_ms", "tpot_p50_ms", "e2e_p99_ms",
                "tokens_per_sec_busy", "requests_completed"):
        assert key in snap, key
    assert snap["queue_wait_p50_ms"] >= 0
    assert snap["e2e_p50_ms"] >= snap["ttft_p50_ms"]


def test_zero_recompiles_at_steady_state_load(model_and_vars):
    """The load harness's compile discipline: after one warm pass over
    a schedule, replaying it mints ZERO compiles (compile_watch-pinned,
    process-wide)."""
    from ml_trainer_tpu.telemetry import compile_watch

    model, variables = model_and_vars
    sched = poisson_schedule(
        60.0, 6, model.vocab_size,
        tenants={"default": TenantLoad(prompt_len=(5, 9),
                                       output_len=(2, 4))},
        seed=2,
    )
    with Server(model, variables, max_batch=2, max_queue=16) as srv:
        run_open_loop(sched, server=srv, time_scale=0.0, timeout=300)
        with compile_watch.expect_no_compiles("steady-state load"):
            run_open_loop(sched, server=srv, timeout=300)


def test_preemption_flight_dump_names_requests(model_and_vars, tmp_path):
    """The forensics acceptance criterion: a forced preemption under
    load yields a flight dump whose ring names the preempted request id
    and whose context attaches that request's lifecycle timeline
    (including its preempt event)."""
    import json

    from ml_trainer_tpu.telemetry.flight import get_recorder

    model, variables = model_and_vars
    rng = np.random.default_rng(5)
    p1 = rng.integers(0, 1024, 9).astype(np.int32)
    p2 = rng.integers(0, 1024, 11).astype(np.int32)
    get_recorder().clear()
    with Server(model, variables, max_batch=2, kv_page_size=8,
                kv_pages=13, prefix_cache=False) as srv:
        s1 = srv.submit(p1, 40, tenant="victim")
        s2 = srv.submit(p2, 40, tenant="victim")
        s1.result(timeout=300)
        s2.result(timeout=300)
        assert srv.metrics.snapshot()["preemptions_total"] >= 1
        path = get_recorder().dump("test preemption", out_dir=str(tmp_path))
    dump = json.loads(open(path).read())
    preempts = [r for r in dump["records"] if r["kind"] == "preempt"]
    assert preempts and isinstance(preempts[0]["request"], int)
    hurt = preempts[0]["request"]
    # decode_step flight records name the requests riding each step.
    steps = [r for r in dump["records"] if r["kind"] == "decode_step"]
    assert steps and any(hurt in r.get("requests", []) for r in steps)
    ctx = dump["context"]["serving_requests"]
    tl = next(
        t for t in ctx["recent"] + ctx["active"] if t["id"] == hurt
    )
    events = [e["event"] for e in tl["events"]]
    assert "preempt" in events and "requeued" in events
    assert events.count("admitted") >= 2  # original + resume
    assert tl["preemptions"] >= 1 and tl["state"] == "done"


def test_slo_http_endpoint_and_unhealthy_dump_names_requests(
        model_and_vars):
    """GET /slo serves the attainment snapshot over the real HTTP front
    end, and an engine-death dump carries the active request ids."""
    import json
    import urllib.request

    model, variables = model_and_vars
    with Server(model, variables, max_batch=2) as srv:
        srv.complete(np.asarray([3, 1, 4], np.int32), 3, timeout=300)
        host, port = srv.serve_http(port=0)
        with urllib.request.urlopen(
            f"http://{host}:{port}/slo", timeout=30
        ) as resp:
            slo = json.loads(resp.read())
    assert slo["requests_observed"] == 1
    assert set(slo["attainment"]) == {"ttft", "tpot"}
    assert "policy" in slo and slo["policy"]["target"] == 0.99
