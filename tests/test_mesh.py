"""Mesh-construction unit layer (fast lane): axis ordering, hybrid
DCNxICI slice factoring.  The training-trajectory integration tests for
the same module live in test_parallel.py (slow lane)."""

import jax
import numpy as np
import pytest

from ml_trainer_tpu.parallel import create_mesh, mesh_shape_for


def test_mesh_shape_for():
    assert mesh_shape_for(8) == {
        "data": 8, "fsdp": 1, "stage": 1, "expert": 1, "sequence": 1,
        "tensor": 1,
    }
    assert mesh_shape_for(8, tensor=2)["data"] == 4
    with pytest.raises(ValueError):
        mesh_shape_for(8, tensor=3)


def test_create_mesh_axes():
    mesh = create_mesh({"data": 4, "tensor": 2})
    assert mesh.axis_names == ("data", "tensor")
    assert mesh.devices.shape == (4, 2)


def test_hybrid_mesh_falls_back_on_single_slice():
    """No slice_index on the CPU mesh -> create_hybrid_mesh must produce
    the same mesh create_mesh would, so multi-slice code rehearses here."""
    from ml_trainer_tpu.parallel.mesh import create_hybrid_mesh, create_mesh

    shape = {"data": 4, "tensor": 2}
    hybrid = create_hybrid_mesh(shape)
    plain = create_mesh(shape)
    assert hybrid.axis_names == plain.axis_names
    assert hybrid.shape == plain.shape
    assert [d.id for d in hybrid.devices.flat] == [
        d.id for d in plain.devices.flat
    ]


def test_hybrid_mesh_dcn_factoring():
    """The slice count factors out of the first divisible dcn axis; the
    elementwise ici*dcn product always reproduces the requested dims."""
    from ml_trainer_tpu.parallel.mesh import _split_dcn

    # data spans 2 slices and keeps a 4-way ICI remainder.
    ici, dcn = _split_dcn(["data", "tensor"], [8, 4], ("data",), 2)
    assert (ici, dcn) == ([4, 4], [2, 1])
    # data == slice count exactly: all of it goes to DCN.
    ici, dcn = _split_dcn(["data", "tensor"], [4, 2], ("data",), 4)
    assert (ici, dcn) == ([1, 2], [4, 1])
    # single slice: nothing to factor.
    ici, dcn = _split_dcn(["data"], [8], ("data",), 1)
    assert (ici, dcn) == ([8], [1])
    # slice count factors ACROSS dcn axes: 4 slices over data=2 x fsdp=2
    # (no single axis could absorb 4 — the greedy-gcd generalization).
    ici, dcn = _split_dcn(
        ["data", "fsdp", "tensor"], [2, 2, 4], ("data", "fsdp"), 4
    )
    assert (ici, dcn) == ([1, 1, 4], [2, 2, 1])
    # partial absorption per axis: 6 slices over data=4 (takes 2), fsdp=3.
    ici, dcn = _split_dcn(["data", "fsdp"], [4, 3], ("data", "fsdp"), 6)
    assert (ici, dcn) == ([2, 1], [2, 3])
    # no dcn axis can absorb the slices -> explicit error.
    import pytest as _pytest

    with _pytest.raises(ValueError, match="cannot span"):
        _split_dcn(["tensor"], [8], ("data",), 2)
    with _pytest.raises(ValueError, match="cannot span"):
        _split_dcn(["data"], [3], ("data",), 2)  # not divisible
