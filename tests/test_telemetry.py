"""Telemetry spine (ml_trainer_tpu/telemetry/).

The contracts worth pinning:

* registry: thread-safe under concurrent writers, idempotent
  registration, Prometheus text exposition matches a golden string;
* spans: Chrome/Perfetto trace-event JSON loads, and same-thread spans
  nest by time containment (how Perfetto renders parent/child);
* flight recorder: bounded ring; an injected ``nan_grad`` FaultPlan
  with rollback produces a dump naming the offending step; an injected
  ``decode_wedge`` produces a serving dump naming the wedged engine
  step;
* step telemetry: ZERO extra compiled programs — the instrumented
  trainer's step compiles exactly once, like the bare trainer's
  (test-pinned cache size), and the trajectory is bit-identical;
* StepTimer: per-step percentiles (fenced, warmup-excluded);
* history.json: JSON-safe mirror written next to the pickle,
  preferred by ``load_history``;
* distributed observability (telemetry/cluster.py): single-host
  degenerate aggregation, straggler detection over an injected pod
  matrix, trace-time collective-comms byte accounting, the run-report
  emission at fit() end, the serving spec-acceptance histogram's real
  Prometheus exposition, and the ``desync_every_steps`` knob (the real
  2-process paths live in tests/test_multiprocess.py).
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel, load_history
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.resilience import faults
from ml_trainer_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    prometheus_text,
    save_trace,
    span,
)
from ml_trainer_tpu.telemetry.flight import get_recorder
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def make_trainer(model_dir, epochs=1, size=64, **kw):
    t = custom_pre_process_function()  # float batches: NaN-poisonable
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=size, seed=0, transform=t),
                  SyntheticCIFAR10(size=32, seed=1, transform=t)),
        epochs=epochs, batch_size=16, model_dir=str(model_dir),
        metric=None, lr=0.01, **kw,
    )


# ---------------------------------------------------------------- registry
def test_registry_thread_safety():
    """N writer threads hammering one counter/gauge/histogram: the
    counter lands on the exact total (a lost update would undercount),
    the histogram's count matches its observations."""
    r = MetricsRegistry()
    c = r.counter("hits_total", "hits", ("worker",))
    g = r.gauge("level")
    h = r.histogram("lat", buckets=(0.5, 1.0))
    n_threads, n_iter = 8, 2000

    def worker(i):
        child = c.labels(worker=str(i % 2))
        for k in range(n_iter):
            child.inc()
            g.set(k)
            h.observe(0.25 if k % 2 else 0.75)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(
        c.labels(worker=str(w)).get() for w in (0, 1)
    )
    assert total == n_threads * n_iter
    assert h.get() is None or True  # labeled access below
    hist = h._get(())
    assert hist["count"] == n_threads * n_iter


def test_registry_idempotent_and_type_checked():
    r = MetricsRegistry()
    a = r.counter("x_total", "first")
    b = r.counter("x_total", "second registration returns the first")
    assert a is b
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x_total")
    with pytest.raises(ValueError, match="metric name"):
        r.counter("bad name")


def test_prometheus_exposition_golden():
    """Pinned text exposition: a scraper-visible format change must be a
    deliberate diff in this golden, not an accident."""
    r = MetricsRegistry()
    c = r.counter("requests_total", "served requests", ("code",))
    c.labels(code=200).inc(3)
    c.labels(code=500).inc()
    r.gauge("queue_depth", "pending requests").set(7)
    h = r.histogram("step_seconds", "step latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    golden = (
        "# HELP requests_total served requests\n"
        "# TYPE requests_total counter\n"
        'requests_total{code="200"} 3\n'
        'requests_total{code="500"} 1\n'
        "# HELP queue_depth pending requests\n"
        "# TYPE queue_depth gauge\n"
        "queue_depth 7\n"
        "# HELP step_seconds step latency\n"
        "# TYPE step_seconds histogram\n"
        'step_seconds_bucket{le="0.1"} 1\n'
        'step_seconds_bucket{le="1"} 2\n'
        'step_seconds_bucket{le="+Inf"} 3\n'
        "step_seconds_sum 5.55\n"
        "step_seconds_count 3\n"
    )
    assert prometheus_text(r) == golden


# ------------------------------------------------------------------- spans
def test_perfetto_trace_loads_and_nests(tmp_path):
    from ml_trainer_tpu.telemetry.spans import clear_trace

    clear_trace()
    with span("outer", step=1):
        with span("inner"):
            pass
    path = save_trace(str(tmp_path / "trace.json"))
    events = json.load(open(path))["traceEvents"]
    by_name = {e["name"]: e for e in events}
    outer, inner = by_name["outer"], by_name["inner"]
    for e in (outer, inner):
        assert e["ph"] == "X" and e["dur"] >= 0
    # Same thread, inner contained in outer: how Perfetto nests.
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"step": 1}


# --------------------------------------------------------- flight recorder
def test_flight_ring_bounded_and_dump(tmp_path):
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("step", step=i)
    recs = fr.records()
    assert [r["step"] for r in recs] == [6, 7, 8, 9]
    path = fr.dump("unit_test", out_dir=str(tmp_path), extra_field=1)
    payload = json.load(open(path))
    assert payload["reason"] == "unit_test"
    assert payload["extra_field"] == 1
    assert len(payload["records"]) == 4


def test_nan_grad_fault_dumps_flight_naming_step(tmp_path, monkeypatch):
    """The acceptance scenario: an injected ``nan_grad`` (with rollback
    armed) must leave a flight dump on disk naming the offending step."""
    monkeypatch.setenv("ML_TRAINER_TPU_FLIGHT_DIR", str(tmp_path))
    get_recorder().clear()
    with faults.injected("nan_grad@step=3"):
        t = make_trainer(
            tmp_path / "m", telemetry=True, log_every_steps=1,
            rollback_bad_steps=1,
        )
        t.fit()
    assert t.rollbacks == 1
    dumps = sorted(
        f for f in os.listdir(tmp_path) if f.startswith("flight_")
    )
    assert dumps, "nan_grad rollback produced no flight dump"
    payload = json.load(open(tmp_path / dumps[0]))
    assert payload["reason"] == "nan_rollback"
    assert payload["first_bad_step"] == 3
    kinds = [r["kind"] for r in payload["records"]]
    assert "nonfinite_steps" in kinds and "rollback" in kinds
    nf = next(r for r in payload["records"] if r["kind"] == "nonfinite_steps")
    assert nf["step"] == 3


def test_decode_wedge_fault_dumps_flight_naming_engine_step(
    tmp_path, monkeypatch
):
    """A wedged decode step trips the watchdog, which dumps the flight
    ring — its newest decode_step record names the wedged step."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import EngineUnhealthy, Server

    monkeypatch.setenv("ML_TRAINER_TPU_FLIGHT_DIR", str(tmp_path))
    get_recorder().clear()
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    # Warm the compiled programs through a throwaway server so the
    # watchdog timeout only has to cover the wedge, not a compile.
    with Server(model, variables, max_batch=2,
                watchdog_timeout=None) as warm:
        warm.complete(np.arange(1, 6, dtype=np.int32), 4, timeout=300)
    with faults.injected("decode_wedge@step=2,secs=30") as plan:
        server = Server(model, variables, max_batch=2,
                        watchdog_timeout=1.0)
        try:
            stream = server.submit(np.arange(1, 6, dtype=np.int32), 16)
            with pytest.raises((RuntimeError, EngineUnhealthy)):
                stream.result(timeout=60)
            assert not server.healthy
        finally:
            plan.release_wedge()
            server.close()
    dumps = sorted(
        f for f in os.listdir(tmp_path) if f.startswith("flight_")
    )
    assert dumps, "watchdog trip produced no flight dump"
    payload = json.load(open(tmp_path / dumps[-1]))
    assert payload["reason"].startswith("serving_unhealthy")
    assert payload["engine_step"] == 2
    steps = [r for r in payload["records"] if r["kind"] == "decode_step"]
    assert steps and steps[-1]["engine_step"] == 2


# ---------------------------------------------------- step telemetry cost
def test_step_telemetry_zero_recompiles_and_identical_trajectory(tmp_path):
    """The acceptance pin: the instrumented train step compiles exactly
    as many programs as the bare one (one), across a full multi-epoch
    fit — and produces the bit-identical parameter trajectory."""
    from ml_trainer_tpu.telemetry import compile_watch

    compile_watch.install()
    before = compile_watch.compile_count("jit(train_step)")
    pw_before = compile_watch.post_warmup_count()
    bare = make_trainer(tmp_path / "bare", epochs=2)
    bare.fit()
    instr = make_trainer(tmp_path / "instr", epochs=2, telemetry=True)
    instr.fit()
    # The real recompile instrument (telemetry/compile_watch.py) replaces
    # the per-function _cache_size() pin: each trainer compiled its train
    # step exactly once across the 2-epoch fit, and nothing compiled
    # after the instrumented run's first epoch closed warmup (deltas —
    # the counters are process-cumulative).
    assert compile_watch.compile_count("jit(train_step)") == before + 2, (
        compile_watch.counts_by_fn()
    )
    assert compile_watch.post_warmup_count() == pw_before, (
        [e.as_dict() for e in compile_watch.events(last=4)]
    )
    for a, b in zip(
        jax.tree.leaves(bare.state.params),
        jax.tree.leaves(instr.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The telemetry actually ran: gauges were published.
    from ml_trainer_tpu.telemetry import default_registry

    snap = default_registry().snapshot()
    assert snap.get("train_steps_total", 0) >= instr.steps_per_epoch


def test_multi_step_dispatch_carries_stats(tmp_path):
    """steps_per_execution > 1: the scanned dispatch returns the last
    step's stats and telemetry still compiles one multi-step program."""
    from ml_trainer_tpu.telemetry import compile_watch

    compile_watch.install()
    before = compile_watch.compile_count("jit(multi_step)")
    t = make_trainer(
        tmp_path / "multi", size=128, telemetry=True,
        steps_per_execution=4,
    )
    t.fit()
    assert compile_watch.compile_count("jit(multi_step)") == before + 1, (
        compile_watch.counts_by_fn()
    )
    from ml_trainer_tpu.telemetry import default_registry

    assert default_registry().snapshot()["train_param_norm"] > 0


# ------------------------------------------------------------- StepTimer
def test_steptimer_percentiles():
    import time as _time

    from ml_trainer_tpu.utils.profiler import StepTimer

    timer = StepTimer(warmup=2, record_steps=True)
    delays = [0.001, 0.001, 0.005, 0.001, 0.02, 0.001, 0.001, 0.001]
    for d in delays:
        _time.sleep(d)
        timer.tick(np.zeros(1), 1)
    p50, p99 = timer.p50(), timer.p99()
    assert p50 is not None and p99 is not None
    assert p99 >= p50
    assert p99 >= 0.015  # the 20ms outlier is in the tail
    assert timer.rate() > 0
    # Default mode records nothing: p50 stays None.
    assert StepTimer(warmup=1).p50() is None


# ---------------------------------------------------------- history.json
def test_history_json_mirror_and_preference(tmp_path):
    t = make_trainer(tmp_path / "h", save_history=True)
    t.fit()
    d = str(tmp_path / "h")
    assert os.path.exists(os.path.join(d, "history.pkl"))
    jpath = os.path.join(d, "history.json")
    assert os.path.exists(jpath)
    hist = json.load(open(jpath))
    assert hist["train_loss"] and "skipped_steps" in hist
    assert hist["rollbacks"] == 0
    # load_history prefers the JSON mirror: poison it with a marker and
    # check the marker comes back (the pickle would not carry it).
    hist["marker"] = "json_wins"
    json.dump(hist, open(jpath, "w"))
    assert load_history(d)["marker"] == "json_wins"
    # Without the mirror, the pickle still loads (the reference path).
    os.remove(jpath)
    assert load_history(d)["train_loss"] == hist["train_loss"]


# ------------------------------------------------- distributed observability
def test_cluster_single_host_aggregation_and_report(tmp_path):
    """Degenerate one-host 'pod': heartbeat -> sync publishes
    cluster_*{host=0} without any collective, no straggler can fire, and
    the run report distills the registry into json + markdown."""
    from ml_trainer_tpu.telemetry import (
        ClusterTelemetry,
        HEARTBEAT_FIELDS,
        write_run_report,
    )

    r = MetricsRegistry()
    fr = FlightRecorder()
    ct = ClusterTelemetry(registry=r, flight=fr)
    ct.heartbeat(last_step=10, step_ms_p50=4.0, step_ms_p99=9.0,
                 samples_per_sec=1200.0)
    gathered = ct.sync(step=10)
    assert gathered.shape == (1, len(HEARTBEAT_FIELDS))
    snap = r.snapshot()
    assert snap["cluster_last_step{host=0}"] == 10.0
    assert snap["cluster_step_ms_p50{host=0}"] == 4.0
    assert snap["cluster_hosts"] == 1
    # One host: nothing to straggle behind.
    assert not any(
        k.startswith("cluster_straggler_events_total") for k in snap
    )
    report = write_run_report(
        str(tmp_path), history={"skipped_steps": [0], "rollbacks": 0},
        registry=r, flight=fr,
    )
    payload = json.load(open(tmp_path / "run_report.json"))
    assert payload["hosts"]["0"]["step_ms_p50"] == 4.0
    assert payload["resilience"]["rollbacks"] == 0
    md = open(tmp_path / "run_report.md").read()
    assert "Per-host heartbeat" in md and "Resilience ledger" in md
    assert report["paths"]["json"].endswith("run_report.json")

    with pytest.raises(ValueError, match="straggler_factor"):
        ClusterTelemetry(registry=r, straggler_factor=1.0)
    with pytest.raises(ValueError, match="unknown heartbeat"):
        ct.heartbeat(nonsense=1.0)


def test_cluster_straggler_detector_on_injected_pod():
    """A fabricated 2-host heartbeat matrix with one slow host must fire
    the counter + flight event naming that host; symmetric times must
    not.  The lower-median rule: on 2 hosts the slow one is compared
    against the FAST one."""
    import numpy as np

    from ml_trainer_tpu.telemetry import ClusterTelemetry, HEARTBEAT_FIELDS

    r = MetricsRegistry()
    fr = FlightRecorder()
    ct = ClusterTelemetry(registry=r, flight=fr, straggler_factor=2.0)
    f = len(HEARTBEAT_FIELDS)
    i50 = HEARTBEAT_FIELDS.index("step_ms_p50")
    even = np.zeros((2, f))
    even[:, i50] = (10.0, 11.0)
    ct._ingest(even, step=5)
    assert not any(
        k.startswith("cluster_straggler_events_total")
        for k in r.snapshot()
    )
    skewed = np.zeros((2, f))
    skewed[:, i50] = (10.0, 25.0)  # 2.5x the fast host
    ct._ingest(skewed, step=7)
    snap = r.snapshot()
    assert snap["cluster_straggler_events_total{host=1}"] == 1
    ev = [rec for rec in fr.records() if rec["kind"] == "straggler"]
    assert ev and ev[-1]["host"] == 1 and ev[-1]["step"] == 7
    assert ev[-1]["cluster_median_ms"] == 10.0
    # Hosts with no data (step_ms 0) neither straggle nor skew the median.
    sparse = np.zeros((2, f))
    sparse[0, i50] = 10.0
    ct._ingest(sparse, step=9)
    assert r.snapshot()["cluster_straggler_events_total{host=1}"] == 1


def test_comm_accounting_formulas_and_traced_bytes():
    """The analytic per-op byte formulas, and the trace-time recording
    through a real shard_map collective on the simulated mesh: zero
    runtime machinery, the gauges carry the analytic number."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.collectives import psum
    from ml_trainer_tpu.parallel.comm_stats import (
        collective_bytes,
        comm_bytes,
        comm_calls,
        reset_comm_stats,
    )
    from ml_trainer_tpu.parallel.compat import shard_map

    # Formula pins (size=1024 bytes, n=4).
    assert collective_bytes("psum", 1024, 4) == 2 * 1024 * 3 / 4
    assert collective_bytes("all_gather", 1024, 4) == 1024 * 3
    assert collective_bytes("reduce_scatter", 1024, 4) == 1024 * 3 / 4
    assert collective_bytes("ppermute", 1024, 4) == 1024
    assert collective_bytes("all_to_all", 1024, 4) == 1024 * 3 / 4
    assert collective_bytes("psum", 1024, 1) == 0.0  # no peers, no bytes
    with pytest.raises(ValueError, match="unknown collective"):
        collective_bytes("gossip", 1, 2)

    reset_comm_stats()
    mesh = create_mesh({"data": 4}, devices=jax.devices()[:4])
    step = jax.jit(shard_map(
        lambda x: psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ))
    step(jnp.ones((8, 4), jnp.float32)).block_until_ready()
    # Per-shard input is (2, 4) f32 = 32 bytes -> ring all-reduce 48.
    assert comm_bytes() == {"psum": 48.0}
    assert comm_calls() == {"psum": 1}
    from ml_trainer_tpu.telemetry import default_registry

    assert default_registry().snapshot()[
        "comm_bytes_total{op=psum}"
    ] == 48.0
    reset_comm_stats()
    assert comm_bytes() == {}
    assert default_registry().snapshot()[
        "comm_bytes_total{op=psum}"
    ] == 0.0


def test_trainer_writes_run_report_and_desync_knob(tmp_path):
    """fit() with telemetry ends by writing run_report.json/.md (the
    degenerate single-host aggregation included); the desync knobs
    validate and are harmless no-ops single-process."""
    with pytest.raises(ValueError, match="desync_every_steps"):
        make_trainer(tmp_path / "bad", desync_every_steps=0)
    with pytest.raises(ValueError, match="straggler_factor"):
        make_trainer(tmp_path / "bad2", straggler_factor=1.0)
    t = make_trainer(
        tmp_path / "m", telemetry=True, desync_every_steps=2,
    )
    t.fit()
    payload = json.load(open(tmp_path / "m" / "run_report.json"))
    assert payload["reason"] == "completed"
    assert payload["hosts"]["0"]["last_step"] == t.steps_per_epoch
    assert payload["resilience"]["rollbacks"] == 0
    assert "checkpoint_writes" in payload
    assert os.path.exists(tmp_path / "m" / "run_report.md")
    from ml_trainer_tpu.telemetry import default_registry

    snap = default_registry().snapshot()
    assert snap["cluster_hosts"] == 1
    assert snap["cluster_syncs_total"] >= 1


def test_serving_spec_histogram_real_exposition():
    """The spec acceptance distribution publishes as the registry's REAL
    Histogram (cumulative le-buckets, histogram_quantile-able), and
    repeated publishes observe only deltas — no double counting."""
    from ml_trainer_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.record_spec([0, 2, 4], draft_k=4)
    r = MetricsRegistry()
    m.publish(r)
    h = r.snapshot()
    assert h["serving_spec_accept_count"] == 3
    assert h["serving_spec_accept_sum"] == 6.0
    m.publish(r)  # idempotent: same cumulative snapshot, no new samples
    assert r.snapshot()["serving_spec_accept_count"] == 3
    m.record_spec([4], draft_k=4)
    m.publish(r)
    assert r.snapshot()["serving_spec_accept_count"] == 4
    text = prometheus_text(r)
    assert "# TYPE serving_spec_accept histogram" in text
    assert 'serving_spec_accept_bucket{le="0"} 1' in text
    assert 'serving_spec_accept_bucket{le="+Inf"} 4' in text
    # The JSON snapshot shape is unchanged (dashboards keep working).
    assert m.snapshot()["spec_accept_hist"] == {"0": 1, "2": 1, "4": 2}


# ------------------------------------------------------------------ flops
def test_analytic_flops_plausible():
    """The analytic accounting must agree with the known published
    numbers within tolerance: ResNet-50 fwd ~8.2 GFLOPs/img @224 (2*MAC
    convention), ViT-B/16 ~35, GPT-2-124M train ~6N per token."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.telemetry.flops import (
        fwd_flops,
        train_step_flops,
    )

    r50 = fwd_flops(get_model("resnet50"), (1, 224, 224, 3))
    assert 7e9 < r50 < 9.5e9
    vit = fwd_flops(get_model("vit_b16"), (1, 224, 224, 3))
    assert 30e9 < vit < 40e9
    gpt2 = train_step_flops(get_model("gpt2"), (1, 1024))
    # 6 * ~163M matmul params (incl. the tied head) * 1024 tokens, plus
    # attention: the right order of magnitude band.
    assert 700e9 < gpt2 < 1200e9
    assert train_step_flops("mlmodel", (32, 32, 32, 3)) > 0
    # Unknown family: None, never zero.
    class Oddball:  # noqa: local stub, not a registered model
        pass

    assert train_step_flops(Oddball(), (1, 8)) is None
