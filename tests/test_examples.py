"""Smoke tests for the example scripts — the executable form of the
reference's notebook flows (SURVEY.md §4: notebooks are its de-facto
integration tests; here the scripts run under pytest on the CPU mesh)."""

import os
import runpy

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def run_example(name, monkeypatch, tmp_path, env):
    import sys

    for k, v in env.items():
        monkeypatch.setenv(k, v)
    monkeypatch.chdir(tmp_path)
    # The scripts read sys.argv (03 takes an optional checkpoint path);
    # pytest's own argv must not leak into them.
    monkeypatch.setattr(sys, "argv", [name])
    # Direct invocation puts the script's dir on sys.path (that is how
    # `import _bootstrap` resolves); runpy.run_path does NOT — mirror it.
    monkeypatch.syspath_prepend(EXAMPLES)
    runpy.run_path(os.path.join(EXAMPLES, name), run_name="__main__")


def test_example_01_then_03_flow(monkeypatch, tmp_path):
    """01 (train→save→load→test) then 03 (inference-only on 01's model) —
    the reference's 01→03 notebook chain."""
    run_example("01_local_training.py", monkeypatch, tmp_path,
                {"MODEL_DIR": str(tmp_path / "m")})
    assert (tmp_path / "m" / "history.pkl").exists()
    run_example("03_testing.py", monkeypatch, tmp_path,
                {"MODEL_DIR": str(tmp_path / "m")})


def test_example_04_gpt2_pretrain(monkeypatch, tmp_path):
    run_example("04_gpt2_pretrain.py", monkeypatch, tmp_path, {
        "MODEL_DIR": str(tmp_path / "g"), "EPOCHS": "1",
        "SYNTH_SIZE": "64", "BATCH": "8", "SEQ_LEN": "32",
        "ACCUM": "2", "K": "2", "REMAT": "1",
    })
    assert (tmp_path / "g" / "history.pkl").exists()


def test_example_05_bert_finetune(monkeypatch, tmp_path):
    run_example("05_bert_finetune.py", monkeypatch, tmp_path, {
        "MODEL_DIR": str(tmp_path / "b"), "EPOCHS": "1", "BATCH": "16",
        "MAX_LEN": "32",
    })
    assert (tmp_path / "b" / "history.pkl").exists()


def test_plot_history_two_and_one_panel(tmp_path):
    """plot_history parity shapes (ref: src/utils/utils.py:31-68):
    2-panel with a metric, 1-panel without, tick thinning past 25."""
    from ml_trainer_tpu.utils.utils import plot_history

    n = 30  # past the 25-epoch tick-thinning threshold
    h2 = {
        "epochs": list(range(1, n + 1)),
        "train_loss": list(np.linspace(2, 1, n)),
        "val_loss": list(np.linspace(2.1, 1.2, n)),
        "train_metric": list(np.linspace(0.3, 0.8, n)),
        "val_metric": list(np.linspace(0.25, 0.75, n)),
        "metric_type": "accuracy",
    }
    fig = plot_history(h2, show=False)
    assert fig is not None and len(fig.axes) == 2
    h1 = dict(h2, train_metric=[], val_metric=[], metric_type=None)
    fig = plot_history(h1, show=False)
    assert fig is not None and len(fig.axes) == 1


def test_main_cli_lm_path(tmp_path):
    """main.py --synthetic_tokens: the transformer families are runnable
    from the reference-shaped CLI entry point (chunked LM loss on)."""
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    r = subprocess.run(
        [sys.executable, os.path.join(root, "main.py"),
         "--synthetic_tokens", "--model", "gpt2_tiny", "--epochs", "1",
         "--batch_size", "8", "--seq_len", "32",
         "--synthetic_train_size", "32", "--synthetic_val_size", "16",
         "--loss_chunk", "16", "--optimizer", "adamw",
         "--backend", "cpu", "--model_dir", str(tmp_path)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Training Complete." in r.stderr + r.stdout


def test_example_06_long_context(monkeypatch, tmp_path):
    run_example("06_long_context.py", monkeypatch, tmp_path, {
        "MODEL_DIR": str(tmp_path / "lc"), "EPOCHS": "1",
        "SYNTH_SIZE": "32", "BATCH": "8", "SEQ_LEN": "64",
        "REMAT": "1", "REMAT_POLICY": "dots", "LOSS_CHUNK": "16",
    })
    assert (tmp_path / "lc" / "history.pkl").exists()


@pytest.mark.parametrize("model", ["gpt2_tiny", "llama_tiny"])
def test_example_08_generation(monkeypatch, tmp_path, model):
    run_example("08_generation.py", monkeypatch, tmp_path, {"MODEL": model})


def test_example_07_streaming_and_elastic(monkeypatch, tmp_path):
    run_example("07_streaming_and_elastic.py", monkeypatch, tmp_path, {
        "MODEL_DIR": str(tmp_path / "sr"), "EPOCHS": "1",
    })
    assert (tmp_path / "sr" / "checkpoints").is_dir()
    # Resume on the same mesh (the elastic cross-device-count variant is
    # tests/test_elastic.py): a second invocation continues cleanly.
    run_example("07_streaming_and_elastic.py", monkeypatch, tmp_path, {
        "MODEL_DIR": str(tmp_path / "sr"), "EPOCHS": "2", "RESUME": "1",
    })
