"""Memory ledger + goodput accounting + recompile forensics
(telemetry/memory.py, goodput.py, compile_watch.py).

The contracts worth pinning:

* **analytic memory model**: the formula walk (``plan_train_memory`` —
  ``jax.eval_shape`` only) agrees with the REAL per-device buffer bytes
  of a built Trainer's state (``measured_tree_bytes`` over
  ``addressable_shards``) for mlmodel and gpt2 across pure-DP, ZeRO-1,
  sharded-dp and pipeline-stash configs — and the division knobs are
  VISIBLE (ZeRO-1 state strictly smaller than replicated);
* **goodput bucket arithmetic**: buckets + the compute remainder
  reconstruct the wall-clock exactly, fractions clamp sanely, unknown
  buckets are rejected;
* **compile-event counter**: a fresh trainer compiles exactly the
  expected programs (named in the counter), steady state compiles
  ZERO; post-warmup compiles produce flight ``recompile`` events
  naming the offending shape;
* **flight context**: dumps attach the registered providers' payloads
  (device-memory snapshot, recent compile events);
* **serving KV pricing**: page geometry × dtype arithmetic and the
  ``serving_kv_pool_bytes{state=}`` gauges.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel
from ml_trainer_tpu.data import SyntheticCIFAR10, SyntheticTokens
from ml_trainer_tpu.telemetry import MetricsRegistry, compile_watch, goodput
from ml_trainer_tpu.telemetry import memory as M
from ml_trainer_tpu.telemetry.flight import FlightRecorder
from ml_trainer_tpu.utils.functions import custom_pre_process_function

TOL = 0.10


def _image_trainer(model_dir, epochs=1, **kw):
    t0 = custom_pre_process_function()
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=64, seed=0, transform=t0),
                  SyntheticCIFAR10(size=32, seed=1, transform=t0)),
        epochs=epochs, batch_size=16, model_dir=str(model_dir),
        metric=None, lr=0.01, optimizer="adamw", **kw,
    )


def _state_measured(trainer) -> float:
    measured, _ = M.measured_tree_bytes({
        "params": trainer.state.params,
        "opt_state": trainer.state.opt_state,
        "batch_stats": trainer.state.batch_stats,
    })
    return measured


def _state_analytic(ledger) -> float:
    return sum(
        c.bytes for c in ledger.components
        if c.name in ("params", "opt_state", "batch_stats")
    )


# ------------------------------------------------------- analytic ledger
@pytest.mark.parametrize("config", [
    {},  # pure DP
    {"shard_opt_state": True},  # ZeRO-1 placement
    {"dp_update": "sharded"},  # sharded update (implies ZeRO-1)
])
def test_mlmodel_analytic_vs_measured(tmp_path, config):
    """Formula ledger vs real buffer bytes across the DP flavors on the
    virtual 8-device data mesh."""
    t = _image_trainer(
        tmp_path / "m", mesh_shape={"data": 8}, **config
    )
    plan = M.plan_train_memory(
        MLModel(), t._batch_geometry, optimizer="adamw",
        mesh_shape={"data": 8},
        shard_opt_state=config.get("shard_opt_state", False),
        dp_update=config.get("dp_update", "fused"),
    )
    check = M.cross_check(_state_analytic(plan), _state_measured(t), TOL)
    assert check["ok"], (config, check)


def test_zero1_division_is_visible(tmp_path):
    """The ÷N is real: ZeRO-1 measured state bytes are strictly below
    the replicated layout's, and the analytic ledger predicts both."""
    rep = _image_trainer(tmp_path / "rep", mesh_shape={"data": 8})
    z1 = _image_trainer(
        tmp_path / "z1", mesh_shape={"data": 8}, shard_opt_state=True
    )
    m_rep, m_z1 = _state_measured(rep), _state_measured(z1)
    assert m_z1 < m_rep
    a_rep = _state_analytic(M.plan_train_memory(
        MLModel(), rep._batch_geometry, optimizer="adamw",
        mesh_shape={"data": 8},
    ))
    a_z1 = _state_analytic(M.plan_train_memory(
        MLModel(), z1._batch_geometry, optimizer="adamw",
        mesh_shape={"data": 8}, shard_opt_state=True,
    ))
    assert a_z1 < a_rep
    assert M.cross_check(a_rep, m_rep, TOL)["ok"]
    assert M.cross_check(a_z1, m_z1, TOL)["ok"]


def test_gpt2_pipeline_stash_ledger(tmp_path):
    """gpt2 pipeline config: stage-sharded stacked params priced within
    10% of the measured buffers, and the trainer's own ledger carries a
    pipeline_stash component sized from the engine's stash accounting."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel import create_mesh, rules_for

    ds = SyntheticTokens(size=16, seq_len=32, vocab_size=256, seed=0)
    mesh = create_mesh({"data": 2, "stage": 4})
    t = Trainer(
        get_model("gpt2_pipe_tiny", mesh=mesh, n_microbatches=4),
        datasets=(ds, ds), epochs=1, batch_size=8, metric=None, lr=0.01,
        optimizer="adamw", model_dir=str(tmp_path / "pp"),
        mesh_shape={"data": 2, "stage": 4},
        sharding_rules=rules_for("gpt2", "pp"),
        pipeline_schedule="1f1b", telemetry=True,
    )
    plan = M.plan_train_memory(
        get_model("gpt2_pipe_tiny", n_microbatches=4),
        t._batch_geometry, optimizer="adamw",
        mesh_shape={"data": 2, "stage": 4},
        sharding_rules=rules_for("gpt2", "pp"),
    )
    check = M.cross_check(_state_analytic(plan), _state_measured(t), TOL)
    assert check["ok"], check
    stash = t._memory_ledger.component("pipeline_stash")
    assert stash is not None and stash.bytes > 0
    # gpt2 also prices the chunked-LM-head peak when loss_chunk is on.
    gpt2 = get_model("gpt2_tiny", vocab_size=256, loss_chunk=8)
    led = M.plan_train_memory(gpt2, (4, 32), optimizer="adamw")
    lc = led.component("loss_chunk_peak")
    assert lc is not None
    assert lc.bytes == 4 * 8 * 256 * 4 * 2  # b x chunk x V x f32 x fwd+bwd


def test_ledger_publish_and_live_snapshot():
    r = MetricsRegistry()
    led = M.MemoryLedger([
        M.Component("params", 1000, "resident"),
        M.Component("grads", 500, "transient"),
    ])
    assert led.resident_bytes() == 1000
    assert led.peak_bytes() == 1500
    led.publish(registry=r)
    snap = r.snapshot()
    assert snap["mem_analytic_bytes{component=params}"] == 1000
    assert snap["mem_analytic_peak_bytes"] == 1500
    anchor = jnp.ones((1024,), jnp.float32)  # guarantee a live buffer
    anchor.block_until_ready()
    live = M.publish_live_memory(registry=r)
    assert live["devices"], live
    assert live["max_bytes_in_use"] > 0
    assert any(
        k.startswith("mem_live_bytes{device=") for k in r.snapshot()
    )


def test_fit_verdict_and_capacity_table():
    from ml_trainer_tpu.telemetry.flops import chip_hbm_capacity_bytes

    cap = chip_hbm_capacity_bytes()
    assert cap > 2 ** 30
    assert M.fit_verdict(0.5 * cap)["verdict"] == "fits"
    assert M.fit_verdict(0.95 * cap)["verdict"] == "tight"
    oom = M.fit_verdict(1.5 * cap)
    assert oom["verdict"] == "oom" and oom["utilization"] > 1.0


# ------------------------------------------------------- goodput buckets
def test_goodput_bucket_arithmetic():
    """Buckets + compute remainder == wall-clock, exactly."""
    base = goodput.snapshot()
    goodput.account("data_wait", 1.0)
    goodput.account("compile", 2.5)
    goodput.account("ckpt_stall", 0.5)
    d = goodput.decompose(10.0, base=base)
    assert d["buckets_secs"]["data_wait"] == pytest.approx(1.0)
    assert d["compute_secs"] == pytest.approx(6.0)
    assert d["goodput_fraction"] == pytest.approx(0.6)
    recon = d["compute_secs"] + sum(d["buckets_secs"].values())
    assert recon == pytest.approx(d["wall_secs"])
    # Overlapping accounting cannot go negative — it is surfaced.
    d2 = goodput.decompose(2.0, base=base)
    assert d2["compute_secs"] == 0.0
    assert d2["overshoot_secs"] == pytest.approx(2.0)
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        goodput.account("nonsense", 1.0)


def test_goodput_timed_and_meter():
    import time as _time

    base = goodput.snapshot()
    with goodput.timed("h2d"):
        _time.sleep(0.01)
    now = goodput.snapshot()
    assert now["h2d"] - base["h2d"] >= 0.009
    r = MetricsRegistry()
    meter = goodput.GoodputMeter(registry=r)
    assert meter.report() is None  # not started
    meter.start()
    _time.sleep(0.005)
    d = meter.report()
    assert 0.0 <= d["goodput_fraction"] <= 1.0
    snap = r.snapshot()
    assert "train_goodput_fraction" in snap
    assert "train_goodput_seconds_total{bucket=h2d}" in snap


# --------------------------------------------------- compile forensics
def test_compile_counter_fresh_vs_steady(tmp_path):
    """A fresh telemetry trainer compiles its train step exactly once
    (named in the counter); a second epoch compiles NOTHING."""
    compile_watch.install()
    before = compile_watch.compile_count("jit(train_step)")
    pw_before = compile_watch.post_warmup_count()
    t = _image_trainer(tmp_path / "cw", epochs=2, telemetry=True)
    t.fit()
    assert compile_watch.compile_count("jit(train_step)") == before + 1, (
        compile_watch.counts_by_fn()
    )
    assert compile_watch.post_warmup_count() == pw_before
    # The labeled counter reached the registry.
    from ml_trainer_tpu.telemetry import default_registry

    snap = default_registry().snapshot()
    assert snap.get("compile_events_total{fn=jit(train_step)}", 0) >= 1


def test_recompile_event_names_offending_shape():
    """A post-warmup compile fires a flight ``recompile`` record whose
    explanation names the argument and shape that missed the cache."""
    compile_watch.install()
    from ml_trainer_tpu.telemetry.flight import get_recorder

    rec = get_recorder()

    @jax.jit
    def poked(x):
        return x * 3.0

    # Inputs built BEFORE warmup closes: jnp.ones itself compiles tiny
    # helper programs that must not pollute the post-warmup count.
    a4 = jnp.ones((4,), jnp.float32)
    a6 = jnp.ones((6,), jnp.float32)
    poked(a4)  # warmup compile
    compile_watch.mark_warm()
    try:
        before = compile_watch.post_warmup_count()
        poked(a4)  # cached: no event
        assert compile_watch.post_warmup_count() == before
        poked(a6)  # shape change: recompile
        assert compile_watch.post_warmup_count() == before + 1
        events = [r for r in rec.records() if r["kind"] == "recompile"]
        assert events, "no flight recompile record"
        last = events[-1]
        assert "poked" in last["fn"]
        assert last["explanation"] and "f32[6]" in last["explanation"], last
    finally:
        compile_watch.mark_cold()


def test_expect_no_compiles_guard():
    compile_watch.install()

    @jax.jit
    def g(x):
        return x + 1

    g(jnp.ones((3,)))
    with compile_watch.expect_no_compiles("steady"):
        g(jnp.ones((3,)))  # cached — fine
    with pytest.raises(AssertionError, match="unexpected compile"):
        with compile_watch.expect_no_compiles("steady"):
            g(jnp.ones((5,)))


# ------------------------------------------------------- flight context
def test_flight_dump_attaches_context(tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.record("step", n=1)
    rec.register_context_provider("memory", M.memory_snapshot_payload)
    rec.register_context_provider(
        "compile_events", lambda: compile_watch.recent_events_payload(4)
    )
    rec.register_context_provider(
        "broken", lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    path = rec.dump("test", out_dir=str(tmp_path))
    import json

    payload = json.load(open(path))
    ctx = payload["context"]
    assert "live" in ctx["memory"]
    assert isinstance(ctx["compile_events"], list)
    assert "boom" in ctx["broken"]  # a broken provider never kills a dump


# ------------------------------------------------------- serving pricing
def test_kv_pool_bytes_and_gauges():
    assert M.kv_pool_bytes(
        n_pages=10, page_size=16, num_heads=2, head_dim=8, n_layers=3,
        dtype=jnp.float32,
    ) == 10 * 2 * 16 * 8 * 4 * 3 * 2
    from ml_trainer_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.record_kv(free=3, used=2, total=5, prefix_nodes=0,
                bytes_per_page=1024)
    snap = m.snapshot()
    assert snap["kv_pool_bytes"] == {
        "free": 3072, "used": 2048, "total": 5120,
    }
    r = MetricsRegistry()
    m.publish(registry=r)
    rsnap = r.snapshot()
    assert rsnap["serving_kv_pool_bytes{state=free}"] == 3072
    assert rsnap["serving_kv_pool_bytes{state=used}"] == 2048


# ------------------------------------------------------- run report ride
def test_run_report_has_memory_goodput_compile_sections(tmp_path):
    t = _image_trainer(tmp_path / "rr", telemetry=True)
    t.fit()
    import json
    import os

    report = json.load(
        open(os.path.join(str(tmp_path / "rr"), "run_report.json"))
    )
    assert "analytic_components" in report["memory"]
    assert report["memory"]["analytic_components"].get("params", 0) > 0
    gp = report["goodput"]
    assert 0.0 <= gp["goodput_fraction"] <= 1.0
    assert "compile" in gp["buckets_secs"]
    assert report["compiles"]["total"] >= 1
    assert "jit(train_step)" in report["compiles"]["by_fn"]
    # The heartbeat schema grew the goodput field.
    from ml_trainer_tpu.telemetry import default_registry

    snap = default_registry().snapshot()
    assert "cluster_goodput_fraction{host=0}" in snap
