"""Mixed precision (precision.py) — policy resolution, dynamic loss
scaling composed with the non-finite guard and gradient accumulation, and
the gradient-bucket planner behind the sharded DP update.

The distributed trajectory-equality pins for dp_update='sharded' live in
tests/test_parallel.py (slow tier); this module is the fast lane:
single-device Trainer runs and pure-host units.
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.precision import (
    LossScaleConfig,
    Precision,
    cast_floating,
    resolve_loss_scale,
    resolve_precision,
)
from ml_trainer_tpu.resilience import faults
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def make_trainer(model_dir, **kw):
    t = custom_pre_process_function()  # float batches: NaN-poisonable
    kw.setdefault("epochs", 1)
    kw.setdefault("batch_size", 16)
    kw.setdefault("lr", 0.01)
    kw.setdefault("metric", None)
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=32, seed=0, transform=t),
                  SyntheticCIFAR10(size=16, seed=1, transform=t)),
        model_dir=str(model_dir), **kw,
    )


# ------------------------------------------------------------------ units
def test_precision_policy_resolution():
    assert not resolve_precision(None).active
    assert not resolve_precision("fp32").active
    p = resolve_precision("bf16")
    assert p.active and jnp.dtype(p.compute) == jnp.dtype(jnp.bfloat16)
    assert jnp.dtype(p.params) == jnp.dtype(jnp.float32)
    assert p.label() == "bfloat16"
    # Instances pass through; a non-fp32 master is rejected (the master
    # copy IS the TrainState — changing it would change every checkpoint).
    assert resolve_precision(p) is p
    with pytest.raises(ValueError, match="params"):
        resolve_precision(Precision(params=jnp.bfloat16))
    with pytest.raises(ValueError, match="unknown precision"):
        resolve_precision("fp8")


def test_loss_scale_resolution():
    fp32, bf16 = resolve_precision("fp32"), resolve_precision("bf16")
    # fp32 NEVER scales — the scale arithmetic must not enter the
    # fp32 program (bit-identity).
    assert resolve_loss_scale("dynamic", fp32) is None
    assert resolve_loss_scale(None, bf16) is None
    dyn = resolve_loss_scale("dynamic", bf16)
    assert dyn.growth_factor == 2.0 and dyn.backoff_factor == 0.5
    static = resolve_loss_scale(1024.0, bf16)
    assert static.init_scale == static.min_scale == static.max_scale == 1024.0
    assert static.growth_factor == 1.0  # pinned: never moves
    with pytest.raises(ValueError, match="positive"):
        resolve_loss_scale(-1.0, bf16)
    with pytest.raises(ValueError, match="dynamic"):
        resolve_loss_scale("auto", bf16)


def test_cast_floating_skips_integers():
    tree = {"w": jnp.ones((2,), jnp.float32), "ids": jnp.ones((2,), jnp.int32)}
    out = cast_floating(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32


def test_plan_grad_buckets_reverse_order_and_rule():
    from ml_trainer_tpu.parallel import plan_grad_buckets

    tree = {
        "a": jnp.zeros((16, 4)),   # sharded (16 % 8 == 0)
        "b": jnp.zeros((5,)),      # NOT sharded (5 % 8)
        "c": jnp.zeros((64,)),     # sharded
        "d": jnp.zeros((8, 8)),    # sharded
    }
    plan = plan_grad_buckets(tree, 8, bucket_bytes=300)
    assert plan.sharded == (True, False, True, True)
    # Reverse flatten order (backward production order), every sharded
    # leaf covered exactly once, bound respected (one leaf may exceed it).
    flat = [i for b in plan.buckets for i in b]
    assert flat == [3, 2, 0]
    assert sum(plan.bucket_bytes) == 16 * 4 * 4 + 64 * 4 + 8 * 8 * 4
    # Overlap: everything but the LAST bucket (earliest layers, produced
    # last in the backward) can hide under remaining compute.
    assert plan.overlap_fraction == pytest.approx(
        1.0 - plan.bucket_bytes[-1] / sum(plan.bucket_bytes)
    )
    # n=1 degenerates: nothing shards.
    plan1 = plan_grad_buckets(tree, 1, bucket_bytes=300)
    assert all(plan1.sharded)  # every dim-0 divides 1...
    assert plan_grad_buckets(tree, 7).sharded == (False, False, False, False)


# ----------------------------------------------- scaling x accum x guard
def test_dynamic_scale_halves_on_overflow_without_burning_rollback(tmp_path):
    """The satellite matrix: loss scaling x grad accumulation x NaN guard.
    An injected non-finite step under bf16+dynamic scaling must (a) skip
    the update, (b) halve the scale, (c) land in the skipped-step ledger,
    and (d) NOT advance the rollback streak — overflow is the scale's
    fault, not the run's."""
    with faults.injected("nan_grad@step=2"):
        t = make_trainer(
            tmp_path / "bf16", precision="bf16", grad_accum_steps=2,
        )
        s0 = float(t.state.loss_scale)
        t.fit()
    assert float(t.state.loss_scale) == s0 * 0.5
    assert t.skipped_steps == [1]
    assert int(jax.device_get(t.state.bad_streak)) == 0
    assert all(np.isfinite(t.train_losses))


def test_fp32_ledger_unchanged_by_the_scaling_feature(tmp_path):
    """fp32 control: the same injected NaN advances skipped AND the
    rollback streak exactly as before the feature, and the state carries
    no scale leaves (fp32 checkpoints/pytree unchanged)."""
    with faults.injected("nan_grad@step=2"):
        t = make_trainer(tmp_path / "fp32", log_every_steps=100)
        t.fit()
    assert t.skipped_steps == [1]
    assert int(jax.device_get(t.state.bad_streak)) == 1
    assert t.state.loss_scale is None and t.state.good_steps is None


def test_dynamic_scale_grows_after_interval(tmp_path):
    t = make_trainer(
        tmp_path, precision="bf16",
        loss_scale=LossScaleConfig(init_scale=256.0, growth_interval=2),
        epochs=2,
    )
    t.fit()  # 4 finite steps at growth_interval=2 -> two doublings
    assert float(t.state.loss_scale) == 1024.0
    assert all(np.isfinite(t.train_losses))


def test_static_scale_never_moves(tmp_path):
    with faults.injected("nan_grad@step=1"):
        t = make_trainer(tmp_path, precision="bf16", loss_scale=512.0)
        t.fit()
    # Overflowed once AND trained on: a pinned scale stays pinned.
    assert float(t.state.loss_scale) == 512.0
    assert t.skipped_steps == [1]


def test_bf16_resume_keeps_scale(tmp_path):
    cfg = LossScaleConfig(init_scale=256.0, growth_interval=2)
    t = make_trainer(tmp_path, precision="bf16", loss_scale=cfg)
    t.fit()  # 2 steps -> one doubling to 512
    assert float(t.state.loss_scale) == 512.0
    t2 = make_trainer(tmp_path, precision="bf16", loss_scale=cfg, epochs=2)
    t2.fit(resume=True)
    # The restored run continued from the checkpointed 512, not a
    # re-seeded 256 (one more doubling in its second epoch).
    assert float(t2.state.loss_scale) == 1024.0


def test_scaling_requires_guard():
    with pytest.raises(ValueError, match="guard"):
        Trainer(
            MLModel(), precision="bf16", nonfinite_guard=False,
            model_dir=tempfile.mkdtemp(),
        )
    # Bare bf16 (no scaling) composes with a disabled guard.
    Trainer(
        MLModel(), precision="bf16", loss_scale=None, nonfinite_guard=False,
        model_dir=tempfile.mkdtemp(),
    )


def test_dp_update_validation():
    from ml_trainer_tpu.parallel import rules_for

    with pytest.raises(ValueError, match="fused.*sharded|sharded.*fused"):
        Trainer(MLModel(), dp_update="bucketed", model_dir=tempfile.mkdtemp())
    with pytest.raises(ValueError, match="pure data-parallel"):
        Trainer(
            MLModel(), dp_update="sharded", is_parallel=True, backend="cpu",
            mesh_shape={"data": 4, "tensor": 2},
            sharding_rules=rules_for("gpt2", "tp"),
            model_dir=tempfile.mkdtemp(),
        )
    with pytest.raises(ValueError, match="steps_per_execution"):
        Trainer(
            MLModel(), dp_update="sharded", is_parallel=True, backend="cpu",
            steps_per_execution=4, model_dir=tempfile.mkdtemp(),
        )
    # Single-replica mesh: nothing to shard -> documented fused fallback.
    t = Trainer(MLModel(), dp_update="sharded", model_dir=tempfile.mkdtemp())
    assert t.dp_update == "fused"
