"""Real multi-process distributed execution (VERDICT r2 #6).

The rest of the suite runs single-process on a simulated 8-device mesh,
which leaves the genuinely multi-host branches dead: the
``jax.distributed.initialize`` rendezvous, the per-host sampler split +
``make_array_from_process_local_data`` assembly in ``prefetch_to_device``,
``_resume_from_latest``'s broadcast, and ``check_desync``.  This test
launches TWO worker processes (4 virtual CPU devices each → one 8-device
cluster) and runs them all — the TPU-native analog of rehearsing the
reference's SMDDP path with multiple real processes rather than one
process pretending (SURVEY.md §4).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two_workers(worker, tmp_path, markers):
    port = _free_port()
    env = dict(os.environ)
    # The workers build their own device topology; drop the suite's flags.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # Collect what each worker managed to say (communicate() on the
        # finished ranks already closed their pipes — reuse those outputs).
        for p in procs:
            p.kill()
        for p in procs[len(outs):]:
            try:
                out, _ = p.communicate(timeout=10)
            except Exception:
                out = "<no output recovered>"
            outs.append(out)
        pytest.fail("multi-process workers timed out:\n" + "\n".join(outs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        for marker in markers:
            assert marker in out, f"rank {rank} missing {marker}:\n{out}"
    # Both hosts observed the SAME global losses (one logical run).
    losses = [
        line for out in outs for line in out.splitlines()
        if line.startswith("LOSSES ")
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses
    return outs


@pytest.mark.slow
def test_two_process_training_resume_and_desync(tmp_path):
    """The full multi-host loop, now including distributed observability:
    CLUSTER_AGG_OK pins that every host's registry carries BOTH hosts'
    ``cluster_*{host=...}`` heartbeat series after training (so host 0's
    scrape covers the pod), STRAGGLER_OK that a forced-slow host trips
    the straggler counter + flight event naming it, and
    DESYNC_FORENSICS_OK that the forced-desync negative case leaves a
    registry fingerprint on every host plus a flight record AND an
    on-disk dump naming the diverging host and step."""
    _run_two_workers(
        _WORKER, tmp_path,
        ("LOSSES", "DESYNC_CLEAN_OK", "CLUSTER_AGG_OK", "STRAGGLER_OK",
         "RESUME_OK", "DESYNC_FORCED_OK", "DESYNC_FORENSICS_OK",
         "WORKER_DONE"),
    )


@pytest.mark.slow
def test_two_process_sharded_checkpoint(tmp_path):
    """ZeRO-1 state checkpointed with every process writing only its own
    shards (v3), proven from the piece tables, then resumed without any
    host-0 gather/broadcast (VERDICT r3 #4)."""
    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "mp_sharded_worker.py"
    )
    outs = _run_two_workers(
        worker, tmp_path,
        ("LOSSES", "SHARD_LAYOUT_OK", "RESUME_OK", "WORKER_DONE"),
    )
    # The post-resume param fingerprint agrees across hosts — the sharded
    # restore reassembled identical replicas.
    fps = {
        line.rsplit("fp=", 1)[1]
        for out in outs for line in out.splitlines()
        if line.startswith("RESUME_OK ")
    }
    assert len(fps) == 1, fps
