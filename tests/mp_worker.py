"""Worker process for tests/test_multiprocess.py.

Each worker is one "host" of a 2-process CPU cluster: 4 local virtual
devices, ``jax.distributed.initialize`` rendezvous, then the code paths
that are dead under the usual single-process simulated mesh (SURVEY.md §4
implication (c)): the per-host sampler split + multi-host prefetch
assembly (``make_array_from_process_local_data``), rank-0 checkpointing
with the broadcast resume, and the cross-host desync detector — including
a forced-desync negative case.

Usage: python mp_worker.py <coordinator_port> <process_id> <workdir>
"""

import os
import sys

port, pid, workdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

# CPU pin must be the in-process config update — the interpreter site hook
# pins an experimental TPU platform that env vars cannot override.
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ml_trainer_tpu import MLModel, Trainer  # noqa: E402
from ml_trainer_tpu.data import SyntheticCIFAR10  # noqa: E402
from ml_trainer_tpu.parallel.desync import check_desync  # noqa: E402
from ml_trainer_tpu.utils.functions import (  # noqa: E402
    custom_pre_process_function,
)

transform = custom_pre_process_function()  # normalize — raw 0-255 pixels
# make the loss scale meaningless for the cross-rank equality check
datasets = (
    SyntheticCIFAR10(size=64, seed=0, transform=transform),
    SyntheticCIFAR10(size=32, seed=1, transform=transform),
)
common = dict(
    batch_size=16, model_dir=workdir, is_parallel=True, backend="cpu",
    seed=5, lr=0.001, optimizer="adam", metric=None,
)

# --- multi-host training: sampler split + prefetch assembly + desync check
t = Trainer(MLModel(), datasets=datasets, epochs=2, **common)
sampler = t.train_loader.sampler
assert getattr(sampler, "num_replicas", 1) == 2, sampler
t.fit()
assert all(np.isfinite(v) for v in t.train_losses)
print(f"LOSSES {t.train_losses}", flush=True)

# --- healthy state: fingerprints agree across hosts
check_desync({"params": t.state.params})
print("DESYNC_CLEAN_OK", flush=True)

# --- resume: host 0 finds the checkpoint, decision + state broadcast
t2 = Trainer(MLModel(), datasets=datasets, epochs=3, **common)
t2.fit(resume=True)
assert len(t2.train_losses) == 3, t2.train_losses
assert t2.train_losses[:2] == t.train_losses, (t2.train_losses, t.train_losses)
print(f"RESUME_OK {t2.train_losses}", flush=True)

# --- forced desync: perturb THIS host's local replica only (host-local
# numpy copies; a global-array op would need every process to join in)
local = jax.tree.map(
    lambda p: np.asarray(p.addressable_data(0)), t2.state.params
)
if pid == 1:
    local = jax.tree.map(lambda a: a + 100.0, local)
try:
    check_desync(local)
    detected = False
except RuntimeError:
    detected = True
# Only the diverged (non-zero) host compares against host 0's broadcast.
assert detected == (pid == 1), (detected, pid)
print("DESYNC_FORCED_OK", flush=True)
print("WORKER_DONE", flush=True)
