"""Worker process for tests/test_multiprocess.py.

Each worker is one "host" of a 2-process CPU cluster: 4 local virtual
devices, ``jax.distributed.initialize`` rendezvous, then the code paths
that are dead under the usual single-process simulated mesh (SURVEY.md §4
implication (c)): the per-host sampler split + multi-host prefetch
assembly (``make_array_from_process_local_data``), rank-0 checkpointing
with the broadcast resume, the cross-host desync detector — including a
forced-desync negative case with registry/flight forensics — and the
distributed-observability layer (telemetry/cluster.py): real cross-host
heartbeat aggregation into ``cluster_*{host=...}`` series, plus a
forced-slow host tripping the straggler detector.

Usage: python mp_worker.py <coordinator_port> <process_id> <workdir>
"""

import os
import sys

port, pid, workdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
# Per-host flight-dump dir (the workers share `workdir` as their shared
# checkpoint storage; dumps are asserted per host below).
flight_dir = os.path.join(workdir, f"flight_host{pid}")
os.environ["ML_TRAINER_TPU_FLIGHT_DIR"] = flight_dir

import jax  # noqa: E402

# CPU pin must be the in-process config update — the interpreter site hook
# pins an experimental TPU platform that env vars cannot override.
jax.config.update("jax_platforms", "cpu")
# Cross-process CPU computations (the jitted psum inside
# broadcast_one_to_all / process_allgather, and device_put's cross-host
# value check) need a CPU collectives backend; without gloo the runtime
# raises "Multiprocess computations aren't implemented on the CPU
# backend".  Must be set before the first device use.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert len(jax.local_devices()) == 4

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ml_trainer_tpu import MLModel, Trainer  # noqa: E402
from ml_trainer_tpu.data import SyntheticCIFAR10  # noqa: E402
from ml_trainer_tpu.parallel.desync import check_desync  # noqa: E402
from ml_trainer_tpu.utils.functions import (  # noqa: E402
    custom_pre_process_function,
)

transform = custom_pre_process_function()  # normalize — raw 0-255 pixels
# make the loss scale meaningless for the cross-rank equality check
datasets = (
    SyntheticCIFAR10(size=64, seed=0, transform=transform),
    SyntheticCIFAR10(size=32, seed=1, transform=transform),
)
common = dict(
    batch_size=16, model_dir=workdir, is_parallel=True, backend="cpu",
    seed=5, lr=0.001, optimizer="adam", metric=None,
    # Distributed observability rides the telemetry flag: heartbeats at
    # every sync, ONE cluster allgather per epoch (telemetry/cluster.py).
    # The factor is cranked way up so NATURAL skew between two worker
    # processes sharing one CPU never fires; the forced-straggler test
    # below tightens it deterministically.
    telemetry=True, log_every_steps=1, straggler_factor=50.0,
)

# --- multi-host training: sampler split + prefetch assembly + desync check
t = Trainer(MLModel(), datasets=datasets, epochs=2, **common)
sampler = t.train_loader.sampler
assert getattr(sampler, "num_replicas", 1) == 2, sampler
t.fit()
assert all(np.isfinite(v) for v in t.train_losses)
print(f"LOSSES {t.train_losses}", flush=True)

# --- healthy state: fingerprints agree across hosts
check_desync({"params": t.state.params})
print("DESYNC_CLEAN_OK", flush=True)

# --- cluster aggregation: EVERY host's registry now carries both hosts'
# heartbeat series (the allgather republishes the whole pod everywhere,
# so host 0's scrape covers it — and so does this host's assert).
from ml_trainer_tpu.telemetry import default_registry  # noqa: E402

snap = default_registry().snapshot()
for h in (0, 1):
    assert f"cluster_last_step{{host={h}}}" in snap, sorted(
        k for k in snap if k.startswith("cluster_")
    )
    assert snap[f"cluster_last_step{{host={h}}}"] > 0, snap
assert snap.get("cluster_hosts") == 2, snap
print("CLUSTER_AGG_OK", flush=True)

# --- forced straggler: host 1 reports a 10x step time into its
# heartbeat; the next aggregation must fire the detector on BOTH hosts'
# registries (the gathered view is identical) naming host 1.
ct = t._cluster
ct.straggler_factor = 2.0  # identical on both hosts: detection stays
# deterministic (it runs on the gathered matrix, same on every host)
base_ms = max(float(snap["cluster_step_ms_p50{host=0}"]), 1.0)
ct.heartbeat(step_ms_p50=base_ms * (10.0 if pid == 1 else 1.0))
ct.sync(step=12345)
snap = default_registry().snapshot()
assert snap.get("cluster_straggler_events_total{host=1}", 0) >= 1, snap
assert "cluster_straggler_events_total{host=0}" not in snap or (
    snap["cluster_straggler_events_total{host=0}"] == 0
), snap
straggler_recs = [
    r for r in t._flight.records() if r["kind"] == "straggler"
]
assert straggler_recs and straggler_recs[-1]["host"] == 1, straggler_recs
assert straggler_recs[-1]["step"] == 12345, straggler_recs
print("STRAGGLER_OK", flush=True)

# --- resume: host 0 finds the checkpoint, decision + state broadcast
t2 = Trainer(MLModel(), datasets=datasets, epochs=3, **common)
t2.fit(resume=True)
assert len(t2.train_losses) == 3, t2.train_losses
assert t2.train_losses[:2] == t.train_losses, (t2.train_losses, t.train_losses)
print(f"RESUME_OK {t2.train_losses}", flush=True)

# --- forced desync: perturb THIS host's local replica only (host-local
# numpy copies; a global-array op would need every process to join in)
local = jax.tree.map(
    lambda p: np.asarray(p.addressable_data(0)), t2.state.params
)
if pid == 1:
    local = jax.tree.map(lambda a: a + 100.0, local)
try:
    check_desync(local, step=777)
    detected = False
except RuntimeError:
    detected = True
# Only the diverged (non-zero) host compares against host 0's broadcast.
assert detected == (pid == 1), (detected, pid)
print("DESYNC_FORCED_OK", flush=True)

# --- desync forensics: every host published its fingerprint; the
# diverging host ALSO left a flight record + an on-disk dump naming
# itself and the step, all BEFORE the RuntimeError above unwound.
snap = default_registry().snapshot()
assert f"cluster_param_fingerprint{{host={pid}}}" in snap, sorted(
    k for k in snap if k.startswith("cluster_param")
)
from ml_trainer_tpu.telemetry.flight import get_recorder  # noqa: E402

desync_recs = [
    r for r in get_recorder().records() if r["kind"] == "desync"
]
if pid == 1:
    assert desync_recs, "diverging host recorded no desync event"
    assert desync_recs[-1]["host"] == 1, desync_recs
    assert desync_recs[-1]["step"] == 777, desync_recs
    assert snap.get("cluster_desync_events_total", 0) >= 1, snap
    import json  # noqa: E402

    dumps = sorted(
        f for f in os.listdir(flight_dir) if f.startswith("flight_")
    )
    assert dumps, "diverging host wrote no flight dump"
    payloads = [
        json.load(open(os.path.join(flight_dir, f))) for f in dumps
    ]
    desync_dumps = [p for p in payloads if p["reason"] == "desync"]
    assert desync_dumps, [p["reason"] for p in payloads]
    assert desync_dumps[-1]["host"] == 1 and desync_dumps[-1]["step"] == 777
else:
    assert not desync_recs, desync_recs
print("DESYNC_FORENSICS_OK", flush=True)
print("WORKER_DONE", flush=True)
