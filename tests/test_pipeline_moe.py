"""Pipeline parallelism (GPipe scan over the ``stage`` axis) and
expert-parallel MoE — the two strategies VERDICT r1 #10 required behind the
reserved mesh axes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.models.moe import MoEMLP
from ml_trainer_tpu.parallel import (
    create_mesh,
    pipeline_apply,
    rules_for,
    stack_stage_params,
)

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


# ----------------------------------------------------------------- pipeline
def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(n_stages, width, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(0, 0.5, (width, width)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (width,)), jnp.float32),
        }
        for _ in range(n_stages)
    ]


def _serial(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_serial(n_micro):
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stages = _make_stages(4, 16)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(16, 16)), jnp.float32
    )
    out = pipeline_apply(
        _stage_fn, stacked, x, mesh, n_microbatches=n_micro
    )
    np.testing.assert_allclose(out, _serial(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_under_jit_and_grad():
    """The schedule is one lax.scan: jit-able and reverse-differentiable —
    gradients equal the serial composition's."""
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stages = _make_stages(4, 8, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, 8)), jnp.float32)

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)

    def loss_serial(ps):
        return jnp.sum(_serial(ps, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_serial = jax.grad(loss_serial)(stages)
    g_serial_stacked = stack_stage_params(g_serial)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_serial_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pipeline_remat_matches_stored_activations():
    """remat=True recomputes stage bodies in the backward — identical
    values AND gradients to the stored-activation schedule."""
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stages = _make_stages(4, 8, seed=5)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(7).normal(size=(8, 8)), jnp.float32)

    def loss(p, remat):
        return jnp.sum(
            pipeline_apply(_stage_fn, p, x, mesh, remat=remat) ** 2
        )

    v_plain, g_plain = jax.jit(
        jax.value_and_grad(lambda p: loss(p, False))
    )(stacked)
    v_remat, g_remat = jax.jit(
        jax.value_and_grad(lambda p: loss(p, True))
    )(stacked)
    np.testing.assert_allclose(v_plain, v_remat, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_pipeline_rejects_indivisible_batch():
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked = stack_stage_params(_make_stages(4, 8))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(
            _stage_fn, stacked, jnp.ones((6, 8)), mesh, n_microbatches=4
        )


# ------------------------------------------------- tick-table schedules
def _serial_loss_of(stacked, x):
    def loss(p):
        out, _ = jax.lax.scan(
            lambda c, pv: (_stage_fn(pv, c), None), x, p
        )
        return jnp.sum(out ** 2)

    return jax.value_and_grad(loss)(stacked)


@pytest.mark.parametrize(
    "schedule,n_dev,n_virtual",
    [
        ("gpipe", 2, 1), ("gpipe", 4, 1),
        ("1f1b", 2, 1), ("1f1b", 4, 1),
        ("zb", 2, 1), ("zb", 4, 1),
        ("interleaved", 2, 2), ("interleaved", 4, 2),
    ],
)
def test_pipeline_schedule_equivalence_matrix(schedule, n_dev, n_virtual):
    """Every schedule is the SAME math as the serial fold — value AND
    gradient — across M in {S, 2S, 4S}, with and without remat.  The
    tick tables only move WHERE each stage runs and WHEN."""
    n_total = n_dev * n_virtual
    mesh = create_mesh({"stage": n_dev}, devices=jax.devices()[:n_dev])
    stacked = stack_stage_params(_make_stages(n_total, 8, seed=n_total))
    for m_factor in (1, 2, 4):
        M = n_total * m_factor
        x = jnp.asarray(
            np.random.default_rng(M).normal(size=(2 * M, 8)), jnp.float32
        )
        vs, gs = _serial_loss_of(stacked, x)
        for remat in (False, True):
            v, g = jax.jit(jax.value_and_grad(
                lambda p: jnp.sum(pipeline_apply(
                    _stage_fn, p, x, mesh, n_microbatches=M,
                    schedule=schedule, n_virtual=n_virtual, remat=remat,
                ) ** 2)
            ))(stacked)
            np.testing.assert_allclose(float(v), float(vs), rtol=1e-5)
            for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gs)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4,
                    err_msg=f"{schedule} M={M} remat={remat}",
                )


def test_pipeline_zero_recompile_across_schedules():
    """At fixed shapes each schedule stays ONE compiled program across
    repeated calls, and swapping schedules never retraces an already-
    compiled one (separate jit closures, each pinned at cache size 1)."""
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked = stack_stage_params(_make_stages(4, 8, seed=1))
    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(16, 8)), jnp.float32
    )
    fns = {}
    for schedule in ("gpipe", "1f1b", "zb"):
        fns[schedule] = jax.jit(jax.value_and_grad(
            lambda p, schedule=schedule: jnp.sum(pipeline_apply(
                _stage_fn, p, x, mesh, n_microbatches=8,
                schedule=schedule,
            ) ** 2)
        ))
    for _ in range(2):  # interleave calls round-robin: no retraces
        for schedule, fn in fns.items():
            jax.block_until_ready(fn(stacked))
    for schedule, fn in fns.items():
        assert fn._cache_size() == 1, (schedule, fn._cache_size())


def test_pipeline_validates_knobs():
    """Clear errors for the degenerate configs: M < total stages (every
    schedule needs the full ramp), virtual stages outside interleaved,
    unknown schedule names, and a stage stack that does not match the
    mesh x virtual geometry."""
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked = stack_stage_params(_make_stages(4, 8))
    x = jnp.ones((8, 8))
    with pytest.raises(ValueError, match="full ramp"):
        pipeline_apply(_stage_fn, stacked, x, mesh, n_microbatches=2)
    with pytest.raises(ValueError, match="full ramp"):
        pipeline_apply(
            _stage_fn, stacked, x, mesh, n_microbatches=2, schedule="1f1b"
        )
    with pytest.raises(ValueError, match="interleaved"):
        pipeline_apply(
            _stage_fn, stacked, x, mesh, n_microbatches=4,
            schedule="1f1b", n_virtual=2,
        )
    with pytest.raises(ValueError, match="unknown schedule"):
        pipeline_apply(
            _stage_fn, stacked, x, mesh, n_microbatches=4,
            schedule="pipedream",
        )
    with pytest.raises(ValueError, match="leading stage dim"):
        pipeline_apply(
            _stage_fn, stacked, x, mesh, n_microbatches=8,
            schedule="interleaved", n_virtual=2,  # needs 8 stages, has 4
        )
    with pytest.raises(ValueError, match="pipeline_schedule"):
        Trainer(
            get_model("gpt2_pipe_tiny"), pipeline_schedule="pipedream",
        )
    with pytest.raises(ValueError, match="schedule"):
        Trainer(get_model("mlmodel"), pipeline_schedule="1f1b")


def test_pipeline_1f1b_bubble_and_comm_accounting():
    """The analytic tick-table facts behind the perf claim: at S=4/M=8
    1F1B's executed-compute waste beats GPipe's (the GPipe scan burns
    bubble slots on garbage compute; the engine skips idle slots), the
    slot-idle bubble matches the closed form for both, and the per-hop
    byte ledger attributes forward hops, backward hops and the output
    broadcast separately."""
    from ml_trainer_tpu.parallel import pipeline_schedule_info
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_hop_bytes,
        reset_comm_stats,
    )
    from ml_trainer_tpu.parallel.pipeline import reset_pipeline_info

    reset_comm_stats()
    reset_pipeline_info()
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked = stack_stage_params(_make_stages(4, 8, seed=3))
    x = jnp.asarray(
        np.random.default_rng(4).normal(size=(16, 8)), jnp.float32
    )
    for schedule in ("gpipe", "1f1b"):
        jax.jit(jax.grad(
            lambda p, schedule=schedule: jnp.sum(pipeline_apply(
                _stage_fn, p, x, mesh, n_microbatches=8,
                schedule=schedule,
            ) ** 2)
        ))(stacked)
    info = pipeline_schedule_info()
    # Slot-idle bubble: the classic (S-1)/(S+M-1) ramp for both.
    assert info["gpipe"]["bubble_fraction"] == pytest.approx(3 / 11, abs=1e-3)
    assert info["1f1b"]["bubble_fraction"] == pytest.approx(3 / 11, abs=1e-3)
    # Executed-compute waste: 1F1B strictly below GPipe at S=4/M=8.
    assert (info["1f1b"]["wasted_compute_fraction"]
            < info["gpipe"]["wasted_compute_fraction"])
    hops = comm_hop_bytes()
    assert {"fwd", "output_broadcast"} <= set(hops["gpipe"])
    assert {"fwd", "bwd", "output_broadcast",
            "grad_input_broadcast"} <= set(hops["1f1b"])
    # The ring broadcast moves half the bytes of the old full psum:
    # (S-1)/S x size vs 2 (S-1)/S x size.
    y_bytes = 8 * 2 * 8 * 4  # [n_micro=8, mb=2, feat=8] fp32 per device
    assert hops["gpipe"]["output_broadcast"] == pytest.approx(
        y_bytes * 3 / 4, rel=1e-6
    )


def test_pipeline_1f1b_trains_dp_x_pp(tmp_path):
    """dp x pp composition under the tick-table engine: gpt2_pipe_tiny
    with pipeline_schedule='1f1b' on a {data:2, stage:4} mesh matches
    the serial-fold trajectory (the engine's hand-written backward must
    psum stage grads across data replicas itself — the regression this
    test pins)."""
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    common = dict(
        epochs=2, batch_size=8, seed=3, lr=0.01, optimizer="adamw",
        metric=None,
    )
    t_serial = Trainer(
        get_model("gpt2_pipe_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "serial"), **common,
    )
    t_serial.fit()
    mesh = create_mesh({"data": 2, "stage": 4})
    t_pp = Trainer(
        get_model("gpt2_pipe_tiny", mesh=mesh, n_microbatches=4),
        datasets=(ds, ds), model_dir=str(tmp_path / "pp"),
        is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "stage": 4},
        sharding_rules=rules_for("gpt2", "pp"),
        pipeline_schedule="1f1b",
        **common,
    )
    assert t_pp.model.schedule == "1f1b"  # the knob really cloned
    t_pp.fit()
    np.testing.assert_allclose(
        t_serial.train_losses, t_pp.train_losses, rtol=1e-3
    )
    np.testing.assert_allclose(
        t_serial.val_losses, t_pp.val_losses, rtol=1e-3
    )
    assert t_pp._train_step._cache_size() == 1


# ---------------------------------------------------------------------- moe
def test_moe_single_expert_equals_dense_mlp():
    """E=1 with ample capacity: routing is the identity, so the MoE layer is
    exactly its one expert MLP (gate prob = softmax over 1 = 1.0)."""
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 8, 16)), jnp.float32
    )
    moe = MoEMLP(num_experts=1, hidden_dim=32, capacity_factor=2.0)
    variables = moe.init({"params": jax.random.PRNGKey(0)}, x)
    out = moe.apply(variables, x)
    p = variables["params"]
    ref = jax.nn.gelu(x @ p["wi"][0]) @ p["wo"][0]
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_moe_routes_and_balances():
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(4, 16, 32)), jnp.float32
    )
    moe = MoEMLP(num_experts=4, hidden_dim=64)
    variables = moe.init({"params": jax.random.PRNGKey(1)}, x)
    out, state = moe.apply(variables, x, mutable=["losses"])
    assert out.shape == x.shape
    aux = state["losses"]["moe_aux_loss"][0]
    # Aux loss is >= 1 (perfect balance) by Cauchy-Schwarz; finite.
    assert float(aux) >= 0.99 and np.isfinite(float(aux))


def test_moe_trains_expert_parallel(tmp_path):
    """gpt2_moe_tiny trains on a {data:2, expert:4} mesh with EP rules:
    expert weights really shard the expert axis and the loss is finite."""
    from jax.sharding import PartitionSpec as P

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)
    t = Trainer(
        get_model("gpt2_moe_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "expert": 4},
        sharding_rules=rules_for("gpt2", "ep"),
        epochs=1, batch_size=8, metric=None, optimizer="adamw",
    )
    wi = t.state.params["block0"]["mlp"]["wi"]
    assert wi.sharding.spec == P("expert", None, None)
    t.fit()
    assert np.isfinite(t.train_losses[0])


def test_moe_aux_loss_applied_in_train_step(tmp_path):
    """VERDICT r2 #3: the sown load-balance loss must be CONSUMED by the
    train step, not just computed.  With a huge ``moe_aux_weight`` the
    recorded training loss is dominated by the aux term (>= weight * 1.0,
    the perfect-balance lower bound); with weight 0 it is ordinary
    cross-entropy scale."""
    ds = SyntheticTokens(size=16, seq_len=16, vocab_size=256, seed=0)

    def run(weight):
        t = Trainer(
            get_model("gpt2_moe_tiny"), datasets=(ds, ds),
            model_dir=str(tmp_path), epochs=1, batch_size=8,
            metric=None, optimizer="sgd", lr=0.0,
            moe_aux_weight=weight,
        )
        assert t._has_aux_losses
        t.fit()
        return t.train_losses[0]

    base = run(0.0)
    boosted = run(1000.0)
    # gpt2_moe_tiny has MoE in both of its two blocks; each layer's aux
    # is >= 1.0 by Cauchy-Schwarz, so the boosted loss must sit >= 2000
    # above the plain loss (assert with slack).
    assert boosted - base >= 1800.0


def test_moe_aux_loss_rebalances_collapsed_router():
    """Behavioral check: start from a router biased hard onto expert 0 and
    train on random data.  With the aux loss the expert-assignment entropy
    recovers toward log(E); without it the collapse persists."""
    import optax

    e, m, hidden, tokens = 4, 16, 32, 256
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, tokens, m)), jnp.float32)
    target = jnp.asarray(rng.normal(size=(1, tokens, m)), jnp.float32)
    moe = MoEMLP(num_experts=e, hidden_dim=hidden, capacity_factor=2.0)
    variables = moe.init({"params": jax.random.PRNGKey(0)}, x)
    params = variables["params"]
    # Force the collapse: bias the router onto expert 0.
    params["router"]["bias"] = params["router"]["bias"].at[0].add(4.0)

    def entropy_of(params):
        logits = x.reshape(-1, m) @ params["router"]["kernel"] + params[
            "router"
        ]["bias"]
        frac = np.bincount(
            np.asarray(jnp.argmax(logits, axis=-1)), minlength=e
        ) / float(tokens)
        nz = frac[frac > 0]
        return float(-(nz * np.log(nz)).sum())

    def train(params, aux_weight, steps=150):
        tx = optax.adam(0.01)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                out, mut = moe.apply(
                    {"params": p}, x, mutable=["losses"]
                )
                mse = jnp.mean((out - target) ** 2)
                aux = sum(jax.tree.leaves(mut["losses"]))
                return mse + aux_weight * aux

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        for _ in range(steps):
            params, opt_state = step(params, opt_state)
        return params

    assert entropy_of(params) < 0.3  # collapsed at start
    with_aux = train(params, 0.02, steps=300)
    without_aux = train(params, 0.0, steps=300)
    ent_with, ent_without = entropy_of(with_aux), entropy_of(without_aux)
    # log(4) = 1.386; the aux loss must restore most of it, the bare MSE
    # objective must not.
    assert ent_with > 1.0, ent_with
    assert ent_with > ent_without + 0.5, (ent_with, ent_without)


def test_pipeline_parallel_training_matches_serial(tmp_path):
    """VERDICT r2 #4: pipeline parallelism trains a REAL model through the
    Trainer.  gpt2_pipe_tiny — embedding and tied head outside the trunk,
    4 equal-width block stages stacked [4, ...] and sharded P('stage') —
    trains on a {data:2, stage:4} mesh (dp x pp) and matches the serial
    trajectory of the SAME module folding its stacked params with
    lax.scan on one device."""
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    common = dict(
        epochs=2, batch_size=8, seed=3, lr=0.01, optimizer="adamw",
        metric=None,
    )
    t_serial = Trainer(
        get_model("gpt2_pipe_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "serial"), **common,
    )
    t_serial.fit()

    mesh = create_mesh({"data": 2, "stage": 4})
    t_pp = Trainer(
        get_model("gpt2_pipe_tiny", mesh=mesh, n_microbatches=4),
        datasets=(ds, ds), model_dir=str(tmp_path / "pp"),
        is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "stage": 4},
        sharding_rules=rules_for("gpt2", "pp"),
        **common,
    )
    # The stacked trunk really shards its stage dim.
    for leaf in jax.tree.leaves(t_pp.state.params["blocks"]):
        assert leaf.sharding.spec[0] == "stage", leaf.sharding.spec
    t_pp.fit()
    np.testing.assert_allclose(
        t_serial.train_losses, t_pp.train_losses, rtol=1e-3
    )
    np.testing.assert_allclose(t_serial.val_losses, t_pp.val_losses, rtol=1e-3)


def test_moe_top2_routing():
    """GShard top-2: (a) num_selected=1 reproduces the original top-1
    numbers exactly; (b) with ample capacity, top-2 output equals the
    gate-weighted sum of the two selected experts' dense outputs."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
    kw = dict(num_experts=4, hidden_dim=32, capacity_factor=4.0)
    moe1 = MoEMLP(num_selected=1, **kw)
    variables = moe1.init({"params": jax.random.PRNGKey(2)}, x)
    np.testing.assert_allclose(
        moe1.apply(variables, x),
        MoEMLP(**kw).apply(variables, x),  # default = top-1, same params
        atol=0, rtol=0,
    )

    moe2 = MoEMLP(num_selected=2, **kw)
    out2 = moe2.apply(variables, x)  # router/expert params shape-shared
    p = variables["params"]
    xt = np.asarray(x.reshape(-1, 16))
    probs = jax.nn.softmax(
        xt @ p["router"]["kernel"] + p["router"]["bias"], axis=-1
    )
    topk_p, topk_i = jax.lax.top_k(probs, 2)
    gates = topk_p / jnp.sum(topk_p, axis=-1, keepdims=True)
    expert_out = np.stack(
        [jax.nn.gelu(xt @ p["wi"][j]) @ p["wo"][j] for j in range(4)]
    )  # [E, T, M]
    ref = sum(
        np.asarray(gates[:, s])[:, None]
        * expert_out[np.asarray(topk_i[:, s]), np.arange(xt.shape[0])]
        for s in range(2)
    )
    np.testing.assert_allclose(
        np.asarray(out2).reshape(-1, 16), ref, atol=1e-5, rtol=1e-5
    )


def test_moe_top2_priority_dispatch_drops_second_choices_first():
    """At tight capacity, first choices claim slots before ANY second
    choice.  Checked against an explicit numpy reference that claims
    slots in exactly that order — a dispatch that interleaved choices or
    never dropped would produce different token outputs."""
    e, m, t = 2, 8, 16
    x = jnp.asarray(np.random.default_rng(6).normal(size=(1, t, m)),
                    jnp.float32)
    # capacity = floor(cf * T * K / E) = floor(0.5 * 16 * 2 / 2) = 8.
    # With E=2, K=2 every token selects both experts, so the 16 second
    # choices compete for whatever the 16 first choices left over.
    moe = MoEMLP(num_experts=e, hidden_dim=16, capacity_factor=0.5,
                 num_selected=2)
    variables = moe.init({"params": jax.random.PRNGKey(3)}, x)
    out = moe.apply(variables, x)

    p = variables["params"]
    capacity = 8
    xt = np.asarray(x.reshape(t, m))
    probs = np.asarray(jax.nn.softmax(
        xt @ p["router"]["kernel"] + p["router"]["bias"], axis=-1
    ))
    order = np.argsort(-probs, axis=-1)            # [T, E]: choice ranks
    gates = np.sort(probs, axis=-1)[:, ::-1]
    gates = gates / gates.sum(-1, keepdims=True)
    expert_out = np.stack([
        np.asarray(jax.nn.gelu(xt @ p["wi"][j]) @ p["wo"][j])
        for j in range(e)
    ])
    # Claim slots: ALL first choices in token order, then second choices.
    used = np.zeros(e, int)
    ref = np.zeros_like(xt)
    dropped = 0
    for sel in range(2):
        for tok in range(t):
            ex = order[tok, sel]
            if used[ex] < capacity:
                used[ex] += 1
                ref[tok] += gates[tok, sel] * expert_out[ex, tok]
            else:
                dropped += 1
    assert dropped > 0, "capacity must actually bind for this test"
    np.testing.assert_allclose(
        np.asarray(out).reshape(t, m), ref, atol=1e-5, rtol=1e-5
    )
