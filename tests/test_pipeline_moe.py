"""Pipeline parallelism (GPipe scan over the ``stage`` axis) and
expert-parallel MoE — the two strategies VERDICT r1 #10 required behind the
reserved mesh axes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.models.moe import MoEMLP
from ml_trainer_tpu.parallel import (
    create_mesh,
    pipeline_apply,
    rules_for,
    stack_stage_params,
)


# ----------------------------------------------------------------- pipeline
def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(n_stages, width, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(0, 0.5, (width, width)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (width,)), jnp.float32),
        }
        for _ in range(n_stages)
    ]


def _serial(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_serial(n_micro):
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stages = _make_stages(4, 16)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(16, 16)), jnp.float32
    )
    out = pipeline_apply(
        _stage_fn, stacked, x, mesh, n_microbatches=n_micro
    )
    np.testing.assert_allclose(out, _serial(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_under_jit_and_grad():
    """The schedule is one lax.scan: jit-able and reverse-differentiable —
    gradients equal the serial composition's."""
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stages = _make_stages(4, 8, seed=2)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, 8)), jnp.float32)

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)

    def loss_serial(ps):
        return jnp.sum(_serial(ps, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_serial = jax.grad(loss_serial)(stages)
    g_serial_stacked = stack_stage_params(g_serial)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_serial_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pipeline_rejects_indivisible_batch():
    mesh = create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked = stack_stage_params(_make_stages(4, 8))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(
            _stage_fn, stacked, jnp.ones((6, 8)), mesh, n_microbatches=4
        )


# ---------------------------------------------------------------------- moe
def test_moe_single_expert_equals_dense_mlp():
    """E=1 with ample capacity: routing is the identity, so the MoE layer is
    exactly its one expert MLP (gate prob = softmax over 1 = 1.0)."""
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 8, 16)), jnp.float32
    )
    moe = MoEMLP(num_experts=1, hidden_dim=32, capacity_factor=2.0)
    variables = moe.init({"params": jax.random.PRNGKey(0)}, x)
    out = moe.apply(variables, x)
    p = variables["params"]
    ref = jax.nn.gelu(x @ p["wi"][0]) @ p["wo"][0]
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_moe_routes_and_balances():
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(4, 16, 32)), jnp.float32
    )
    moe = MoEMLP(num_experts=4, hidden_dim=64)
    variables = moe.init({"params": jax.random.PRNGKey(1)}, x)
    out, state = moe.apply(variables, x, mutable=["losses"])
    assert out.shape == x.shape
    aux = state["losses"]["moe_aux_loss"][0]
    # Aux loss is >= 1 (perfect balance) by Cauchy-Schwarz; finite.
    assert float(aux) >= 0.99 and np.isfinite(float(aux))


def test_moe_trains_expert_parallel(tmp_path):
    """gpt2_moe_tiny trains on a {data:2, expert:4} mesh with EP rules:
    expert weights really shard the expert axis and the loss is finite."""
    from jax.sharding import PartitionSpec as P

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)
    t = Trainer(
        get_model("gpt2_moe_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "expert": 4},
        sharding_rules=rules_for("gpt2", "ep"),
        epochs=1, batch_size=8, metric=None, optimizer="adamw",
    )
    wi = t.state.params["block0"]["mlp"]["wi"]
    assert wi.sharding.spec == P("expert", None, None)
    t.fit()
    assert np.isfinite(t.train_losses[0])
