"""Per-host sharded checkpointing (format v3) + elastic restore.

The v2 format funnels the full state through host 0 (``fetch_to_host``
process-allgathers non-addressable leaves) — exactly the host-RAM spike +
DCN gather the sharded format exists to remove: each process writes only
its addressable shards, and restore stitches them back per-device, even
onto a different mesh than the one that saved (the TPU-preemption story;
the reference has neither — SURVEY.md §5 checkpoint/resume).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ml_trainer_tpu import MLModel, Trainer
from ml_trainer_tpu.checkpoint import checkpoint as ckpt
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.parallel import create_mesh


def _mesh_state(mesh, n=16, d=128):
    """A tiny state-like dict with one replicated and one data-sharded
    leaf, plus scalar/None/empty edge cases."""
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    return {
        "params": {
            "w": jax.device_put(
                jnp.arange(n * d, dtype=jnp.float32).reshape(n, d), repl
            ),
        },
        "opt_state": {
            "mu": jax.device_put(
                jnp.arange(n * d, dtype=jnp.float32).reshape(n, d) * 2, row
            ),
            "empty": {},
        },
        "step": jax.device_put(jnp.asarray(7, jnp.int32), repl),
        "none": None,
    }


def test_v3_roundtrip_and_layout(tmp_path):
    mesh = create_mesh({"data": 8})
    state = _mesh_state(mesh)
    ckpt.save_checkpoint_sharded(str(tmp_path), state, {"h": [1.0]}, epoch=3)
    path = os.path.join(str(tmp_path), "checkpoint_3")
    with open(os.path.join(path, "manifest.json")) as fp:
        manifest = json.load(fp)
    assert manifest["format"] == 3 and manifest["epoch"] == 3

    # Layout: the sharded leaf landed as 8 pieces of 2 rows each — never
    # as one full array — while the replicated leaf deduped to ONE piece.
    tables = ckpt._read_piece_tables(path)
    by_path = {tuple(m["path"]): i for i, m in enumerate(manifest["leaves"])}
    mu_pieces = tables[by_path[("opt_state", "mu")]]
    assert len(mu_pieces) == 8
    assert all(stop[0] - start[0] == 2 for start, stop, _, _crc in mu_pieces)
    assert len(tables[by_path[("params", "w")]]) == 1

    # Host-array restore (no shardings).
    restored, history, epoch = ckpt.restore_checkpoint(path, state)
    assert epoch == 3 and history == {"h": [1.0]}
    np.testing.assert_array_equal(
        np.asarray(restored["opt_state"]["mu"]),
        np.asarray(state["opt_state"]["mu"]),
    )
    assert restored["none"] is None and restored["opt_state"]["empty"] == {}

    # Sharded restore onto the SAME mesh: leaves come back with the
    # requested shardings and the right values.
    shardings = jax.tree.map(lambda x: x.sharding, state)
    restored2, _, _ = ckpt.restore_checkpoint(path, state, shardings)
    assert restored2["opt_state"]["mu"].sharding.spec == P("data")
    np.testing.assert_array_equal(
        np.asarray(restored2["params"]["w"]), np.asarray(state["params"]["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored2["opt_state"]["mu"]),
        np.asarray(state["opt_state"]["mu"]),
    )
    assert int(restored2["step"]) == 7


def test_v3_elastic_restore_across_meshes(tmp_path):
    """A checkpoint written 8-way sharded restores onto a 4-device mesh
    (and 2-way sharding) — the piece grid and target shard grid differ."""
    mesh8 = create_mesh({"data": 8})
    state = _mesh_state(mesh8)
    ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=1)
    path = os.path.join(str(tmp_path), "checkpoint_1")

    mesh4 = create_mesh({"data": 4}, devices=jax.devices()[:4])
    target = {
        "params": {"w": NamedSharding(mesh4, P())},
        "opt_state": {"mu": NamedSharding(mesh4, P("data")), "empty": {}},
        "step": NamedSharding(mesh4, P()),
        "none": None,
    }
    restored, _, _ = ckpt.restore_checkpoint(path, state, target)
    mu = restored["opt_state"]["mu"]
    assert mu.sharding.mesh.devices.size == 4
    # Each 4-mesh shard (4 rows) stitched from two saved 2-row pieces.
    assert mu.addressable_shards[0].data.shape[0] == 4
    np.testing.assert_array_equal(
        np.asarray(mu), np.asarray(state["opt_state"]["mu"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), np.asarray(state["params"]["w"])
    )


def test_v3_uncommitted_checkpoint_invisible(tmp_path):
    """A v3 dir without the commit marker (crash before the barrier
    completed) must not be picked up by latest_checkpoint."""
    mesh = create_mesh({"data": 8})
    state = _mesh_state(mesh)
    ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=1)
    ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=2)
    os.remove(os.path.join(str(tmp_path), "checkpoint_2", "manifest.json"))
    latest = ckpt.latest_checkpoint(str(tmp_path))
    assert latest is not None and latest.endswith("checkpoint_1")


def test_v3_resave_crash_leaves_no_commit_marker(tmp_path, monkeypatch):
    """Re-saving an epoch whose directory already holds a committed
    manifest must invalidate that marker BEFORE writing pieces: a crash
    mid-save then yields an uncommitted dir, not a valid marker over
    torn/mixed piece files (ADVICE r4 medium)."""
    mesh = create_mesh({"data": 8})
    state = _mesh_state(mesh)
    ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=1)
    path = os.path.join(str(tmp_path), "checkpoint_1")
    assert os.path.exists(os.path.join(path, "manifest.json"))

    calls = {"n": 0}
    real_save = np.save

    def crash_after_first(fname, arr, **kw):
        calls["n"] += 1
        if calls["n"] > 1:
            raise OSError("disk full (simulated)")
        return real_save(fname, arr, **kw)

    monkeypatch.setattr(np, "save", crash_after_first)
    with pytest.raises(OSError):
        ckpt.save_checkpoint_sharded(str(tmp_path), state, {}, epoch=1)
    monkeypatch.undo()
    # The half-written epoch is invisible — no silent corruption.
    assert not os.path.exists(os.path.join(path, "manifest.json"))
    assert ckpt.latest_checkpoint(str(tmp_path)) is None


@pytest.mark.slow
def test_sharded_checkpoint_carries_batch_stats(tmp_path):
    """BatchNorm state (a mutable collection, not params) must ride the
    v3 format too: a resnet18 run checkpoints sharded and resumes with
    its running mean/var intact — the trajectory continues exactly."""
    from ml_trainer_tpu.models import get_model

    def trainer(epochs):
        return Trainer(
            get_model("resnet18"),
            datasets=(SyntheticCIFAR10(size=32, seed=0),
                      SyntheticCIFAR10(size=16, seed=1)),
            epochs=epochs, batch_size=16, model_dir=str(tmp_path),
            is_parallel=True, backend="cpu", seed=3, lr=0.01,
            optimizer="adam", metric=None, sharded_checkpoint=True,
        )

    t1 = trainer(1)
    t1.fit()
    latest = ckpt.latest_checkpoint(
        os.path.join(str(tmp_path), "checkpoints")
    )
    assert ckpt.checkpoint_format(latest) == 3
    t2 = trainer(2)
    t2.fit(resume=True)
    assert t2.train_losses[0] == pytest.approx(t1.train_losses[0], abs=1e-7)
    # Restored batch_stats equal the saved run's, leaf for leaf.
    restored = ckpt.restore_checkpoint(latest, t1.state)[0]
    for a, b in zip(
        jax.tree.leaves(t1.state.batch_stats),
        jax.tree.leaves(restored.batch_stats),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_elastic_resume_across_tensor_degrees(tmp_path):
    """The strongest re-gridding case: a checkpoint written on a
    {data:4, tensor:2} mesh resumes onto {data:2, tensor:4} — every
    TP-sharded kernel's piece grid changes shape, and ZeRO-type moment
    placement re-divides.  The trajectory must continue the uninterrupted
    mesh-A run (sharding is placement, not math)."""
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel import rules_for

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)

    def trainer(workdir, epochs, mesh_shape):
        return Trainer(
            get_model("gpt2_tiny"), datasets=(ds, ds), epochs=epochs,
            batch_size=16, model_dir=str(workdir), is_parallel=True,
            backend="cpu", seed=21, lr=0.01, optimizer="adamw", metric=None,
            mesh_shape=mesh_shape, sharding_rules=rules_for("gpt2", "tp"),
            sharded_checkpoint=True,
        )

    mesh_a = {"data": 4, "tensor": 2}
    mesh_b = {"data": 2, "tensor": 4}
    full = trainer(tmp_path / "full", 4, mesh_a)
    full.fit()

    t1 = trainer(tmp_path / "el", 2, mesh_a)
    t1.fit()
    t2 = trainer(tmp_path / "el", 4, mesh_b)
    t2.fit(resume=True)
    # Re-gridded placement proven: qkv kernels sharded 4-way now.
    from jax.sharding import PartitionSpec as P

    qkv = t2.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "tensor")
    assert qkv.sharding.mesh.shape["tensor"] == 4
    assert t2.train_losses[:2] == pytest.approx(t1.train_losses, abs=1e-6)
    assert t2.train_losses == pytest.approx(full.train_losses, rel=2e-4)
    # Params to the tolerance different tensor degrees allow: a 4-way
    # psum sums in a different order than a 2-way one EVERY step, and two
    # epochs of adamw amplify that ULP-level noise (see
    # tests/test_all_knobs.py's measured amplification note).  The
    # trajectory assertions above are the correctness claim; this one
    # only guards against gross state corruption.
    for a, b in zip(
        jax.tree.leaves(full.state.params), jax.tree.leaves(t2.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


@pytest.mark.slow
def test_trainer_sharded_checkpoint_trajectory(tmp_path):
    """Trainer(sharded_checkpoint=True) + ZeRO-1: resume continues the
    exact trajectory of an uninterrupted run (the v2-parity guarantee,
    now without any host holding the full tree)."""
    def trainer(workdir, epochs):
        return Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0),
                      SyntheticCIFAR10(size=32, seed=1)),
            epochs=epochs, batch_size=16, model_dir=str(workdir),
            is_parallel=True, backend="cpu", seed=11, lr=0.01,
            optimizer="adam", shard_opt_state=True, sharded_checkpoint=True,
        )

    full = trainer(tmp_path / "full", 4)
    full.fit()

    t1 = trainer(tmp_path / "resume", 2)
    t1.fit()
    ckpt_dir = os.path.join(str(tmp_path / "resume"), "checkpoints")
    latest = ckpt.latest_checkpoint(ckpt_dir)
    assert ckpt.checkpoint_format(latest) == 3
    t2 = trainer(tmp_path / "resume", 4)
    t2.fit(resume=True)
    assert t2.train_losses[:2] == pytest.approx(t1.train_losses, abs=1e-6)
    assert t2.train_losses == pytest.approx(full.train_losses, rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(full.state.params), jax.tree.leaves(t2.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
