"""Multi-process serving fleet (ml_trainer_tpu/serving/fleet.py).

Ground truth is ``generate()``, as everywhere in the serving stack: a
request whose prefill is CHUNKED (windowed across engine-loop
iterations so decode ticks and short admissions interleave), or whose
KV cache crosses a process boundary as serialized bytes over
``POST /v1/adopt``, must still reproduce the standalone batch-1
``generate()`` output byte-for-byte — greedy and seeded-sampling
alike.  The full 4-process fleet (spawned workers, real SIGKILL,
autoscaler respawn) lives in scripts/fleet_smoke.py and the bench
gate's gate_fleet; these tests pin the underlying mechanics with
in-process servers (the socket tests still cross a real HTTP socket —
the servers just live in this process behind ``serve_http``).
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import Router, Server
from ml_trainer_tpu.serving.fleet import RemoteServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(0, 1024, n).astype(
        np.int32
    )


# -- chunked prefill ------------------------------------------------------

def test_chunked_prefill_byte_identity_greedy_and_seeded(model_and_vars):
    """Prompts split into page-aligned windows must land EXACTLY where
    a monolithic prefill would: same KV, same sampler state, same
    tokens — including a seeded sampling stream (the per-request PRNG
    key must survive the deferred first token)."""
    model, variables = model_and_vars
    with Server(model, variables, max_batch=2, kv_page_size=8,
                prefill_chunk=16) as server:
        # 40 and 33 span 3 windows (the last one ragged); 9 rides a
        # single sub-window prefill.
        for n, seed in ((40, 0), (33, 1), (9, 2)):
            p = _prompt(n, seed)
            ref = np.asarray(generate(model, variables, p[None], 12))[0]
            out = np.asarray(server.complete(p, 12, timeout=120))
            np.testing.assert_array_equal(out, ref)
        p = _prompt(40, 3)
        ref = np.asarray(
            generate(model, variables, p[None], 10, temperature=0.7,
                     rng=jax.random.PRNGKey(11))
        )[0]
        out = np.asarray(
            server.complete(p, 10, temperature=0.7, rng=11, timeout=120)
        )
        np.testing.assert_array_equal(out, ref)
        snap = server.metrics.snapshot()
        assert snap["chunked_admissions_total"] >= 3
        assert snap["prefill_chunks_total"] >= 6


def test_chunked_prefill_unblocks_short_ttft(model_and_vars):
    """The adversarial long+short pair: with chunking, a short request
    submitted behind a long prompt gets its first token BEFORE the
    long one (it admits and prefills between the long prompt's
    windows); without chunking the monolithic long prefill
    head-of-line-blocks it, so the long request's first token lands
    first.  Both slots are plugged while the pair enqueues (the pair is
    QUEUED together, so the ordering reflects the engine's admission
    interleave, not client-thread timing) and first-token order is read
    from the engine's own ``first_token_at`` stamps — deterministic,
    not a wall-clock threshold."""
    model, variables = model_and_vars
    long_p, short_p = _prompt(48, 4), _prompt(8, 5)
    ref_long = np.asarray(generate(model, variables, long_p[None], 8))[0]
    ref_short = np.asarray(
        generate(model, variables, short_p[None], 8)
    )[0]

    def first_token_order(chunk):
        # prefix_cache off: the warmups below would otherwise turn the
        # timed long prompt into a full prefix hit whose tiny remainder
        # never chunks.
        with Server(model, variables, max_batch=2, kv_page_size=8,
                    prefill_chunk=chunk, prefix_cache=False) as server:
            # Warm both shapes so compile time doesn't serialize the
            # timed pair.
            server.complete(long_p, 2, timeout=120)
            server.complete(short_p, 2, timeout=120)
            plugs = [
                server.submit(_prompt(8, 50 + i), 16) for i in range(2)
            ]
            s_long = server.submit(long_p, 8)
            s_short = server.submit(short_p, 8)
            for s in plugs:
                s.result(timeout=60)
            np.testing.assert_array_equal(
                np.asarray(s_long.result(timeout=60)), ref_long
            )
            np.testing.assert_array_equal(
                np.asarray(s_short.result(timeout=60)), ref_short
            )
            return (s_long.request.first_token_at,
                    s_short.request.first_token_at)

    # chunk=8 -> the 48-token prompt is 6 windows; the short request
    # admits and monolithic-prefills between them.
    t_long, t_short = first_token_order(chunk=8)
    assert t_short < t_long, (
        f"chunked: short first token at {t_short} not ahead of long "
        f"at {t_long}"
    )
    t_long, t_short = first_token_order(chunk=0)
    assert t_long < t_short, (
        f"unchunked: long prefill should head-of-line-block the short "
        f"request (long at {t_long}, short at {t_short})"
    )


def test_prefill_chunk_validation(model_and_vars):
    model, variables = model_and_vars
    with pytest.raises(ValueError, match="prefill_chunk"):
        Server(model, variables, max_batch=2, prefill_chunk=16)  # contig
    with pytest.raises(ValueError, match="multiple"):
        Server(model, variables, max_batch=2, kv_page_size=8,
               prefill_chunk=12)


# -- socket adopt() round trip -------------------------------------------

def test_socket_adopt_round_trip_bit_exact(model_and_vars):
    """Disaggregated prefill->decode where the KV migration crosses a
    REAL HTTP socket: the router drives two servers through
    ``RemoteServer`` proxies (NDJSON streams, ``POST /v1/adopt``
    carrying the serialized export, CRC verified at the receiving
    process) and the continuation must be bit-exact — greedy and
    seeded."""
    model, variables = model_and_vars
    servers, remotes = {}, {}
    router = None
    try:
        for name, role in (("prefill0", "prefill"), ("decode0", "decode")):
            srv = Server(model, variables, max_batch=2, kv_page_size=8,
                         role=role, prefill_chunk=16)
            host, port = srv.serve_http(port=0)
            servers[name] = srv
            remotes[name] = RemoteServer(
                f"http://{host}:{port}", name=name
            )
        assert all(r.pid == os.getpid() for r in remotes.values())
        assert remotes["prefill0"].role == "prefill"
        router = Router(
            dict(remotes),
            replica_urls={n: r.url for n, r in remotes.items()},
            hedging=False,
        )
        for n, seed in ((40, 6), (12, 7)):
            p = _prompt(n, seed)
            ref = np.asarray(generate(model, variables, p[None], 12))[0]
            out = np.asarray(router.complete(p, 12, timeout=120))
            np.testing.assert_array_equal(out, ref)
        p = _prompt(24, 8)
        ref = np.asarray(
            generate(model, variables, p[None], 10, temperature=0.7,
                     rng=jax.random.PRNGKey(3))
        )[0]
        out = np.asarray(
            router.complete(p, 10, temperature=0.7, rng=3, timeout=120)
        )
        np.testing.assert_array_equal(out, ref)
        snap = router.snapshot()
        assert snap["migrations_total"] >= 3
        assert snap["kv_migrated_bytes_total"] > 0
        # The adopt hop really ran: the decode server (which never saw
        # a client submit) produced decode steps.
        assert servers["decode0"].metrics.snapshot()[
            "decode_steps_total"
        ] > 0
    finally:
        if router is not None:
            router.close()
        for srv in servers.values():
            srv.close()


# -- changed-only gate-leg mapping ---------------------------------------

def _load_bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "scripts", "bench_gate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_changed_only_leg_mapping():
    """`bench_gate.py --changed-only` must select a strict subset on a
    docs-only diff and every leg on a serving diff — the mapping is a
    CI contract (a miss silently skips a gate)."""
    bg = _load_bench_gate()
    assert bg.legs_for_changes(
        ["docs/serving.md", "README.md", "tests/test_fleet.py"]
    ) == set()
    assert bg.legs_for_changes(["docs/serving_fleet_cpu.json"]) == {
        "fleet"
    }
    assert bg.legs_for_changes(
        ["ml_trainer_tpu/serving/router.py"]
    ) == set(bg.ALL_LEGS)
    assert bg.legs_for_changes(
        ["ml_trainer_tpu/resilience/faults.py"]
    ) == {"elastic", "overload", "fleet"}
    # The observability spine rides the legs that read it — the SLO
    # plane, the fleet gate (which pins the federation/trace/bundle
    # invariants), the rollout gate's SLO-burn rollback, and the
    # watchtower gate (TSDB/alerts overhead + detection).
    assert bg.legs_for_changes(
        ["ml_trainer_tpu/telemetry/federation.py"]
    ) == {"slo", "fleet", "deploy", "watchtower"}
    assert bg.legs_for_changes(["docs/watchtower_cpu.json"]) == {
        "watchtower"
    }
    assert bg.legs_for_changes(["docs/fleet_obs_cpu.json"]) == {"fleet"}
    # Unmapped file or unknown diff -> run everything (fail safe).
    assert bg.legs_for_changes(["setup.py"]) == set(bg.ALL_LEGS)
    assert bg.legs_for_changes(None) == set(bg.ALL_LEGS)
