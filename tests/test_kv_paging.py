"""Paged KV cache, radix prefix reuse, multi-tenant scheduling (PR6).

Ground truth stays ``generate()``: a request served through the PAGED
engine — page-table indirection, prefix-cache hits, even a mid-flight
preemption and resume — must reproduce its standalone batch-1
``generate()`` output byte-for-byte, greedy and spec mode alike, and
match the CONTIGUOUS engine token-for-token.  Around that core: pool
refcounting, radix lookup/insert/evict, block-granular copy-on-write,
tenant quotas and weighted admission, preempt-requeue forensics, and
the zero-recompile pin under ragged paged traffic.
"""

import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import _COMPILED, generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import (
    AdmissionError,
    KVPagePool,
    PrefixCache,
    Request,
    Server,
    TenantConfig,
    TenantScheduler,
)

PS = 8  # page size used throughout (max_len=64 -> 8 pages per slot)


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


# ---------------------------------------------------------------- units


def test_kv_pool_alloc_free_refcount():
    pool = KVPagePool(num_pages=9, page_size=PS, max_len=64, max_batch=2)
    assert pool.free_count() == 8 and pool.used_count() == 0
    a = pool.allocate(3)
    assert len(a) == 3 and 0 not in a
    assert pool.allocate(6) is None  # all-or-nothing past capacity
    pool.retain(a[:1])               # shared reference (prefix cache)
    pool.bind_slot(0, a)
    assert pool.slot_page_count(0) == 3
    assert (pool.page_table[0, :3] == a).all()
    assert pool.page_table[0, 3:].sum() == 0  # trash past the chain
    freed = pool.reset_slot(0)
    assert freed == 2                # a[0] still held by the extra ref
    assert pool.free_count() == 7
    assert pool.release(a[:1]) == 1  # last ref drops -> freed
    assert pool.free_count() == 8
    assert pool.reset_slot(0) == 0   # idempotent
    with pytest.raises(ValueError, match="double free"):
        pool.release(a[:1])
    with pytest.raises(ValueError, match="trash"):
        pool.retain([0])
    with pytest.raises(ValueError, match="multiple"):
        KVPagePool(num_pages=9, page_size=7, max_len=64, max_batch=2)


def test_prefix_cache_radix_lookup_insert_evict():
    pool = KVPagePool(num_pages=17, page_size=4, max_len=64, max_batch=2)
    cache = PrefixCache(pool)
    toks = np.arange(12, dtype=np.int32)          # 3 full 4-blocks
    pages = pool.allocate(3)
    assert cache.insert(toks, pages) == 3
    assert len(cache) == 3
    # Full-chain hit pins every page for the caller: allocator ref +
    # cache residency + the lookup pin = 3.
    got, n = cache.lookup(np.concatenate([toks, [99]]), max_blocks=3)
    assert got == pages and n == 12
    assert all(pool.refcount[p] == 3 for p in pages)
    # Divergence inside block 2 -> only block 1 matches.
    div = np.concatenate([toks[:6], [77, 78, 79, 80]]).astype(np.int32)
    got2, n2 = cache.lookup(div, max_blocks=2)
    assert got2 == pages[:1] and n2 == 4
    # Pinned pages are not evictable; cache-residency-only ones are.
    assert cache.evict(10) == 0
    pool.release(got)
    pool.release(got2)
    pool.release(pages)  # the allocator's own reference
    assert all(pool.refcount[p] == 1 for p in pages)
    freed = cache.evict(1)
    assert freed >= 1 and len(cache) == 3 - freed
    # Duplicate insert registers nothing new for already-cached blocks.
    more = pool.allocate(3)
    try:
        assert cache.insert(toks[:8], more[:2]) <= 1
    finally:
        pool.release(more)


def test_prefix_lookup_record_false_skips_stats_and_lru():
    """An unrecorded lookup (the engine's retry of a blocked admission)
    returns and pins the chain but neither bumps the hit-rate stats nor
    re-heats the matched nodes — the older chain stays LRU."""
    pool = KVPagePool(num_pages=17, page_size=4, max_len=64, max_batch=2)
    cache = PrefixCache(pool)
    old = np.arange(8, dtype=np.int32)               # 2 blocks
    new = np.arange(100, 108, dtype=np.int32)        # 2 blocks, younger
    p_old = pool.allocate(2)
    cache.insert(old, p_old)
    p_new = pool.allocate(2)
    cache.insert(new, p_new)
    pool.release(p_old)
    pool.release(p_new)                              # cache-resident only
    got, n = cache.lookup(old, 2, record=False)
    assert got == p_old and n == 8
    pool.release(got)
    assert cache.hits == 0 and cache.misses == 0
    assert cache.hit_tokens == 0 and cache.lookup_tokens == 0
    # The unrecorded walk did not refresh `old`: it is still the LRU
    # chain, so eviction takes it first and leaves `new` resident.
    assert cache.evict(2) == 2
    assert cache.lookup(old, 2, record=False)[1] == 0
    got2, n2 = cache.lookup(new, 2, record=False)
    assert n2 == 8
    pool.release(got2)


def test_prefix_cache_deep_chain_evicts_in_one_call():
    """A deep resident chain drains fully in ONE evict() call (the heap
    pushes each parent as its child is dropped)."""
    pool = KVPagePool(num_pages=17, page_size=4, max_len=64, max_batch=2)
    cache = PrefixCache(pool)
    toks = np.arange(24, dtype=np.int32)             # 6-block chain
    pages = pool.allocate(6)
    assert cache.insert(toks, pages) == 6
    pool.release(pages)                              # cache-resident only
    assert cache.evict(6) == 6
    assert len(cache) == 0 and pool.free_count() == 16


def test_prefix_cache_namespaces_do_not_cross():
    pool = KVPagePool(num_pages=17, page_size=4, max_len=64, max_batch=2)
    cache = PrefixCache(pool)
    toks = np.arange(8, dtype=np.int32)
    pages = pool.allocate(2)
    cache.insert(toks, pages, namespace="tenant-a")
    got_b, n_b = cache.lookup(toks, 2, namespace="tenant-b")
    assert got_b == [] and n_b == 0
    got_a, n_a = cache.lookup(toks, 2, namespace="tenant-a")
    assert got_a == pages and n_a == 8
    pool.release(got_a)
    pool.release(pages)


def test_tenant_scheduler_weighted_admission_quotas_priorities():
    sched = TenantScheduler(
        max_batch=8, max_queue=16,
        tenants={"A": TenantConfig(weight=1.0),
                 "B": TenantConfig(weight=3.0, max_queued=6),
                 "C": TenantConfig(max_active=1)},
    )

    def req(tenant, priority=0):
        r = Request(prompt=np.zeros(2, np.int32), max_new_tokens=2,
                    tenant=tenant, priority=priority)
        sched.submit(r)
        return r

    # Weighted interleave: B (weight 3) admits ~3x as often as A.
    for _ in range(4):
        req("A")
        req("B")
    order = []
    for _ in range(8):
        r, slot = sched.acquire()
        order.append(r.tenant)
        sched.release(slot)
    assert order.count("B") == 4 and order.count("A") == 4
    assert order[:4].count("B") >= 3  # B front-loaded by weight

    # Priority within a tenant beats arrival order; ties keep FIFO.
    low = req("A", priority=0)
    high = req("A", priority=5)
    r, slot = sched.acquire()
    assert r is high
    sched.release(slot)
    r, slot = sched.acquire()
    assert r is low
    # Requeued (preempted) request resumes ahead of later arrivals.
    later = req("A")
    sched.release(slot)
    sched.requeue(r)
    r2, slot = sched.acquire()
    assert r2 is r
    sched.release(slot)
    r3, slot = sched.acquire()
    assert r3 is later
    sched.release(slot)

    # max_active quota: C holds at most one slot however many queue.
    c1, c2 = req("C"), req("C")
    got = sched.acquire()
    assert got is not None and got[0] is c1
    assert sched.acquire() is None  # c2 blocked by the quota
    sched.release(got[1])
    got2 = sched.acquire()
    assert got2 is not None and got2[0] is c2
    sched.release(got2[1])

    # max_queued quota rejects with a structured error naming the tenant.
    for _ in range(6):
        req("B")
    with pytest.raises(AdmissionError, match="tenant 'B'"):
        req("B")


# ------------------------------------------------- paged byte identity


def test_paged_greedy_and_sampled_byte_identity(model_and_vars):
    """Mid-stream joins through the paged engine reproduce standalone
    generate() byte-for-byte AND the contiguous engine token-for-token
    (greedy + seeded sampling)."""
    model, variables = model_and_vars
    pA, pB, pC = _prompt(0, 5), _prompt(1, 3), _prompt(2, 7)
    refA = np.asarray(generate(model, variables, pA[None], 24))[0]
    refB = np.asarray(generate(model, variables, pB[None], 8))[0]
    refC = np.asarray(
        generate(model, variables, pC[None], 8, temperature=0.7,
                 rng=jax.random.PRNGKey(42))
    )[0]
    with Server(model, variables, max_batch=4, kv_page_size=PS) as server:
        sA = server.submit(pA, 24)
        next(iter(sA))  # A actively decoding when B and C join
        sB = server.submit(pB, 8)
        sC = server.submit(pC, 8, temperature=0.7, rng=42)
        outA = sA.result(timeout=120)
        outB = sB.result(timeout=120)
        outC = sC.result(timeout=120)
        snap = server.metrics.snapshot()
    np.testing.assert_array_equal(outA, refA)
    np.testing.assert_array_equal(outB, refB)
    np.testing.assert_array_equal(outC, refC)
    assert snap["max_active_slots"] >= 2
    assert snap["kv_pages_total"] == 4 * (64 // PS)


def test_paged_spec_byte_identity(model_and_vars):
    """The fixed-K verify window reading/writing through page tables
    commits the same greedy stream as generate() and the contiguous
    spec engine."""
    model, variables = model_and_vars
    prompts = [_prompt(20 + i, 4 + i) for i in range(3)]
    refs = [
        np.asarray(generate(model, variables, p[None], 12))[0]
        for p in prompts
    ]
    outs = {}
    for paged in (False, True):
        kwargs = dict(max_batch=2, spec_k=4)
        if paged:
            kwargs["kv_page_size"] = PS
        with Server(model, variables, **kwargs) as server:
            streams = [server.submit(p, 12) for p in prompts]
            outs[paged] = [s.result(timeout=120) for s in streams]
    for ref, a, b in zip(refs, outs[False], outs[True]):
        np.testing.assert_array_equal(a, ref)
        np.testing.assert_array_equal(b, ref)


# ------------------------------------------------------- prefix cache


def test_prefix_hit_skips_prefill_and_matches(model_and_vars):
    """Requests sharing a 3-page prefix: the later ones pin the cached
    pages (token-weighted hit rate ~prefix/prompt) and still match
    generate() byte-for-byte — prefill ran only on their suffixes."""
    model, variables = model_and_vars
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 1024, 3 * PS).astype(np.int32)
    prompts = [
        np.concatenate(
            [shared, rng.integers(0, 1024, 1 + (i % 4)).astype(np.int32)]
        )
        for i in range(6)
    ]
    refs = [
        np.asarray(generate(model, variables, p[None], 10))[0]
        for p in prompts
    ]
    with Server(model, variables, max_batch=4, kv_page_size=PS) as server:
        outs = [server.submit(p, 10) for p in prompts]
        outs = [s.result(timeout=120) for s in outs]
        snap = server.metrics.snapshot()
    for o, r in zip(outs, refs):
        np.testing.assert_array_equal(o, r)
    assert snap["prefix_hits"] >= 5
    assert snap["prefix_tokens_saved"] >= 5 * 3 * PS
    assert snap["prefix_hit_rate"] > 0.5
    # The continuation program actually ran (prefill bypass, not a
    # full prefill that happened to match).
    assert any(
        k[0] == "serve_prefill_paged" for k in _COMPILED._data
    )


def test_prefix_divergence_is_copy_on_write(model_and_vars):
    """A request diverging INSIDE a shared block stops matching at the
    last full block and writes fresh pages — the cached pages are never
    written, so re-serving the original prompt stays byte-identical."""
    model, variables = model_and_vars
    rng = np.random.default_rng(11)
    base = rng.integers(0, 1024, 2 * PS + 4).astype(np.int32)
    diverged = base.copy()
    diverged[2 * PS + 1] ^= 1  # flip a token inside block 3
    refs = {
        "base": np.asarray(generate(model, variables, base[None], 8))[0],
        "div": np.asarray(generate(model, variables, diverged[None], 8))[0],
    }
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        out1 = server.complete(base, 8, timeout=120)
        out_div = server.complete(diverged, 8, timeout=120)
        out2 = server.complete(base, 8, timeout=120)  # re-served after COW
        snap = server.metrics.snapshot()
    np.testing.assert_array_equal(out1, refs["base"])
    np.testing.assert_array_equal(out_div, refs["div"])
    np.testing.assert_array_equal(out2, refs["base"])
    assert snap["prefix_hits"] >= 2


def test_prefix_cache_eviction_keeps_outputs_correct(model_and_vars):
    """A pool too small to retain every finished request's pages forces
    eviction; every later request (hit, partial hit, or miss) still
    matches generate(), and no page leaks when the server drains."""
    model, variables = model_and_vars
    rng = np.random.default_rng(13)
    prompts = [
        rng.integers(0, 1024, 2 * PS + 2).astype(np.int32)
        for _ in range(6)
    ]
    prompts += [p.copy() for p in prompts[:2]]  # revisits after pressure
    refs = [
        np.asarray(generate(model, variables, p[None], 6))[0]
        for p in prompts
    ]
    # 10 allocatable pages: each request needs 3 -> the cache cannot
    # hold more than ~2 finished chains and must evict.
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                kv_pages=11) as server:
        for p, ref in zip(prompts, refs):
            np.testing.assert_array_equal(
                server.complete(p, 6, timeout=120), ref
            )
        snap = server.metrics.snapshot()
    assert snap["kv_pages_used"] + snap["kv_pages_free"] \
        == snap["kv_pages_total"]
    # Whatever is still resident is prefix-cache pages only (<= pool).
    assert snap["kv_pages_used"] <= 10


def test_continuation_window_past_max_len_writes_trash_not_tail(
    model_and_vars
):
    """A prefix hit whose pow2-padded suffix window hangs past max_len
    (c=40, su=18 -> bucket 32, window positions 40..71 on max_len=64)
    while the slot's chain fills EVERY page-table entry: the overflow
    padding writes must land in trash — clipping them into the last
    table slot scatters garbage over the row's REAL tail K/V (positions
    56..63 here, including prompt tokens 56/57; duplicate scatter
    indices, last-write-wins on CPU).  Output argmax can mask that on a
    tiny model, so the last page's K/V is compared against a contiguous
    forward of the same prompt — tight tolerance (the reference is a
    differently-shaped program, so ~1e-6 reduction-order noise is
    expected; the clobber is O(1))."""
    from jax import tree_util
    import jax.numpy as jnp

    from ml_trainer_tpu.generate import _cache_shapes, _empty_cache
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine

    model, variables = model_and_vars
    first = _prompt(50, 5 * PS + 1)                        # caches 5 blocks
    second = np.concatenate(
        [first[:5 * PS], _prompt(51, 18)]                  # p=58: needs all
    ).astype(np.int32)                                     # 8 slot pages
    eng = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    r1 = Request(prompt=first, max_new_tokens=6)
    if eng.admit(r1, 0) == "active":
        while 0 in eng._active:
            eng.step()
    r2 = Request(prompt=second, max_new_tokens=4)
    assert eng.admit(r2, 0) == "active"
    assert r2.prefix_hit_tokens == 5 * PS                  # continuation ran
    chain = eng.pool.slot_pages[0]
    assert len(chain) == eng.pool.pages_per_slot           # table row full
    # Contiguous reference: one decode-mode forward over the whole
    # prompt fills positions 0..57 of a fresh contiguous cache.
    dm = model.clone(decode=True)
    _, mut = dm.apply(
        {"params": eng.params,
         "cache": _empty_cache(_cache_shapes(dm, 1, jnp.int32))},
        second[None, :], train=False, mutable=["cache"],
    )
    ref = {
        tuple(str(k) for k in path): leaf
        for path, leaf in tree_util.tree_flatten_with_path(
            mut["cache"]
        )[0]
    }
    paged = {
        tuple(str(k) for k in path): leaf
        for path, leaf in tree_util.tree_flatten_with_path(eng.cache)[0]
    }
    compared = 0
    for path, ref_leaf in ref.items():
        if ref_leaf.ndim != 4:
            continue
        # Last page, offsets 0..1 hold logical positions 56..57 — the
        # real prompt tail the clipped overflow would have clobbered.
        got = np.asarray(paged[path][chain[-1], :, 0:2, :])
        want = np.asarray(ref_leaf[0, :, 56:58, :])
        np.testing.assert_allclose(
            got, want, rtol=1e-3, atol=1e-4, err_msg=str(path)
        )
        compared += 1
    assert compared >= 2  # cached_key + cached_value, every layer
    # And end-to-end: the continuation-admitted request still matches
    # standalone generate() byte-for-byte.
    ref2 = np.asarray(generate(model, variables, second[None], 4))[0]
    while 0 in eng._active:
        eng.step()
    np.testing.assert_array_equal(
        np.concatenate([second, np.asarray(r2.tokens, np.int32)]), ref2
    )


def test_prefix_cache_is_tenant_scoped(model_and_vars):
    """Tenant B never hits tenant A's cached blocks (the cross-tenant
    residency probe is closed); A keeps hitting its own, and
    prefix_scope='global' restores the old shared behavior."""
    model, variables = model_and_vars
    prompt = _prompt(60, 2 * PS + 2)
    ref = np.asarray(generate(model, variables, prompt[None], 4))[0]
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        sA = server.submit(prompt, 4, tenant="A")
        np.testing.assert_array_equal(sA.result(timeout=120), ref)
        sB = server.submit(prompt, 4, tenant="B")
        np.testing.assert_array_equal(sB.result(timeout=120), ref)
        sA2 = server.submit(prompt, 4, tenant="A")
        np.testing.assert_array_equal(sA2.result(timeout=120), ref)
    assert sB.request.prefix_hit_tokens == 0
    assert sA2.request.prefix_hit_tokens == 2 * PS
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                prefix_scope="global") as server:
        server.submit(prompt, 4, tenant="A").result(timeout=120)
        sB = server.submit(prompt, 4, tenant="B")
        np.testing.assert_array_equal(sB.result(timeout=120), ref)
    assert sB.request.prefix_hit_tokens == 2 * PS
    with pytest.raises(ValueError, match="prefix_scope"):
        Server(model, variables, max_batch=1, kv_page_size=PS,
               prefix_scope="bogus")


# --------------------------------------------- preemption and requeue


def test_preempt_requeue_resume_byte_identity(model_and_vars):
    """Two long generations through a pool that cannot hold both: one
    is preempted (pages freed, request re-queued), resumes from its
    committed tokens, and BOTH streams still match generate()."""
    from ml_trainer_tpu.telemetry.flight import get_recorder

    model, variables = model_and_vars
    p1, p2 = _prompt(30, 9), _prompt(31, 11)
    r1 = np.asarray(generate(model, variables, p1[None], 40))[0]
    r2 = np.asarray(generate(model, variables, p2[None], 40))[0]
    get_recorder().clear()
    # Peak demand 6+7 pages > 12 allocatable; no prefix cache, so
    # preemption is the only relief valve.
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                kv_pages=13, prefix_cache=False) as server:
        s1 = server.submit(p1, 40, tenant="gold")
        s2 = server.submit(p2, 40, tenant="gold")
        o1 = s1.result(timeout=300)
        o2 = s2.result(timeout=300)
        snap = server.metrics.snapshot()
    np.testing.assert_array_equal(o1, r1)
    np.testing.assert_array_equal(o2, r2)
    assert snap["preemptions_total"] >= 1
    assert snap["tenants"]["gold"]["preempted"] >= 1
    assert snap["kv_pages_free"] == snap["kv_pages_total"]  # no leaks
    # Flight forensics name the victim, tenant and cause.
    preempts = [
        r for r in get_recorder().records() if r["kind"] == "preempt"
    ]
    assert preempts, "no flight 'preempt' record"
    assert preempts[0]["tenant"] == "gold"
    assert "page_pressure" in preempts[0]["cause"]
    assert preempts[0]["request"] in (s1.request.id, s2.request.id)


def test_preemption_cap_fails_with_structured_error(model_and_vars):
    """max_preemptions=0: the first preemption converts into a
    structured client error naming the victim, tenant, and cause."""
    model, variables = model_and_vars
    p1, p2 = _prompt(32, 9), _prompt(33, 11)
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                kv_pages=13, prefix_cache=False,
                max_preemptions=0) as server:
        s1 = server.submit(p1, 40, tenant="bronze")
        s2 = server.submit(p2, 40, tenant="bronze")
        results, errors = [], []
        for s in (s1, s2):
            try:
                results.append(s.result(timeout=300))
            except RuntimeError as e:
                errors.append(str(e))
    assert len(errors) == 1, (len(results), errors)
    assert "preempted" in errors[0] and "bronze" in errors[0]
    assert "page pressure" in errors[0]


def test_pool_too_small_is_a_structured_error(model_and_vars):
    """A request whose prompt cannot fit the whole pool fails loudly
    (nothing running will ever free pages) instead of queuing forever."""
    model, variables = model_and_vars
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                kv_pages=3, prefix_cache=False) as server:
        stream = server.submit(_prompt(34, 3 * PS), 4)
        with pytest.raises(RuntimeError, match="kv pool exhausted"):
            stream.result(timeout=60)


# ------------------------------------------------- engine disciplines


def test_paged_zero_recompile_across_ragged_traffic(model_and_vars):
    """After a warm-up wave over the bucket space, a second wave of
    DIFFERENT ragged prompts/budgets/tenants — with prefix hits,
    misses, and page churn — compiles NOTHING new."""
    model, variables = model_and_vars
    rng = np.random.default_rng(17)
    shared = rng.integers(0, 1024, 2 * PS).astype(np.int32)

    def wave(server, seed0):
        local = np.random.default_rng(seed0)
        streams = []
        for i in range(8):
            if i % 2:
                p = np.concatenate([
                    shared,
                    local.integers(0, 1024, 1 + i % 4).astype(np.int32),
                ])
            else:
                p = local.integers(0, 1024, 3 + i % 5).astype(np.int32)
            streams.append(
                server.submit(p, 4 + i % 5, tenant=f"t{i % 2}")
            )
        for s in streams:
            s.result(timeout=120)

    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        wave(server, 100)
        n_warm = len(_COMPILED._data)
        wave(server, 200)
        n_after = len(_COMPILED._data)
    assert n_after == n_warm, (
        f"ragged paged traffic compiled {n_after - n_warm} new program(s)"
    )


def test_paged_metrics_published_to_registry(model_and_vars):
    """KV-pool gauges, prefix hit rate and per-tenant series reach the
    telemetry registry's Prometheus exposition."""
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry

    model, variables = model_and_vars
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        server.complete(_prompt(40, 6), 4, tenant="acme", timeout=120)
        reg = MetricsRegistry()
        server.metrics.publish(reg)
        text = reg.prometheus_text()
    assert "serving_kv_pages_free" in text
    assert "serving_kv_pages_used" in text
    assert "serving_prefix_hit_rate" in text
    assert "serving_preemptions_total" in text
    assert 'serving_tenant_queue_depth{tenant="acme"}' in text
    assert 'serving_tenant_admitted{tenant="acme"} 1' in text


def test_contiguous_engine_rejects_kv_pages_without_page_size(
    model_and_vars
):
    model, variables = model_and_vars
    with pytest.raises(ValueError, match="kv_pages"):
        Server(model, variables, max_batch=1, kv_pages=8)
    with pytest.raises(ValueError, match="divide"):
        Server(model, variables, max_batch=1, kv_page_size=7)
