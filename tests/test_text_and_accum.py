"""Tokenized-text path (BERT/GPT-2 configs) + gradient accumulation +
observability utilities."""

import numpy as np
import pytest

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import (
    Loader,
    PackedLMDataset,
    SyntheticTokens,
    TokenizedDataset,
    tokenize_texts,
)
from ml_trainer_tpu.models import get_model

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


# ------------------------------------------------------------------- text
def test_tokenize_texts_defaults_to_in_tree_fixture_vocab():
    """With no vocab configured anywhere, tokenize_texts now encodes
    with the REAL in-tree tokenizer over the repo's fixture vocabs (the
    hash stand-in is an explicit opt-in, VERDICT order #6)."""
    from ml_trainer_tpu.data.tokenizers import (
        fixture_vocab_dir,
        load_tokenizer,
    )

    ids, mask = tokenize_texts(["a great movie", "terrible"], max_len=16)
    assert ids.shape == (2, 16) and mask.shape == (2, 16)
    tok = load_tokenizer(fixture_vocab_dir())
    ref = tok.encode("a great movie")
    assert list(ids[0][: len(ref)]) == ref
    assert mask[0].sum() == len(ref)
    ids2, _ = tokenize_texts(["a great movie", "terrible"], max_len=16)
    np.testing.assert_array_equal(ids, ids2)  # deterministic


def test_tokenize_texts_hash_is_explicit_opt_in():
    ids, mask = tokenize_texts(
        ["a great movie", "terrible"], max_len=16, tokenizer="hash"
    )
    assert ids[0, 0] == 1  # [CLS]-style framing
    assert mask[0].sum() == 5  # cls + 3 words + sep


def test_tokenized_dataset_and_bert_finetune_flow(tmp_path):
    texts = [f"sample review number {i} {'good' if i % 2 else 'bad'}"
             for i in range(32)]
    labels = [i % 2 for i in range(32)]
    ds = TokenizedDataset.from_texts(texts, labels, max_len=32, vocab_size=1024)
    model = get_model("bert_tiny", num_classes=2, max_len=32)
    trainer = Trainer(
        model, datasets=(ds, ds), epochs=1, batch_size=8,
        model_dir=str(tmp_path), optimizer="adamw", lr=1e-3,
    )
    trainer.fit()
    assert np.isfinite(trainer.train_losses[0])
    assert 0.0 <= trainer.train_metrics[0] <= 1.0


def test_sst2_tsv_loader(tmp_path):
    from ml_trainer_tpu.data import load_sst2_tsv

    path = tmp_path / "train.tsv"
    path.write_text(
        "sentence\tlabel\n"
        "a delightful film\t1\n"
        "worst movie ever\t0\n"
    )
    ds = load_sst2_tsv(str(path), max_len=16)
    assert len(ds) == 2
    assert set(ds.targets.tolist()) == {0, 1}


def test_packed_lm_dataset_next_token_targets():
    stream = np.arange(1000, dtype=np.int32)
    ds = PackedLMDataset(stream, seq_len=64)
    assert len(ds) == 15
    x, y = ds[0]
    np.testing.assert_array_equal(y, x + 1)  # next-token shift


def test_packed_lm_too_short_raises():
    with pytest.raises(ValueError, match="too short"):
        PackedLMDataset(np.arange(10), seq_len=64)


# ------------------------------------------------------- grad accumulation
def test_grad_accum_matches_full_batch(tmp_path):
    """accum=4 must follow the same trajectory as accum=1 at equal global
    batch (the defining property of gradient accumulation)."""
    ds = SyntheticTokens(size=64, seq_len=16, vocab_size=256, seed=0)
    common = dict(
        epochs=2, batch_size=16, seed=11, lr=0.01, metric=None,
        optimizer="sgd", momentum=0.0,
    )
    t1 = Trainer(
        get_model("gpt2_tiny", vocab_size=256, max_len=16),
        datasets=(ds, ds), model_dir=str(tmp_path / "a"), **common,
    )
    t1.fit()
    t4 = Trainer(
        get_model("gpt2_tiny", vocab_size=256, max_len=16),
        datasets=(ds, ds), model_dir=str(tmp_path / "b"),
        grad_accum_steps=4, **common,
    )
    t4.fit()
    np.testing.assert_allclose(t1.train_losses, t4.train_losses, rtol=1e-4)


def test_grad_accum_invalid_raises():
    with pytest.raises(ValueError, match="grad_accum_steps"):
        Trainer(get_model("mlmodel"), epochs=1, batch_size=8,
                grad_accum_steps=0)


# ----------------------------------------------------------- observability
def test_step_timer_reports_rate():
    import jax.numpy as jnp

    from ml_trainer_tpu.utils.profiler import StepTimer

    timer = StepTimer(warmup=2)
    x = jnp.zeros(())
    for _ in range(10):
        x = x + 1.0
        timer.tick(x, 32)
    rate = timer.rate()
    assert rate is not None and rate > 0


def test_param_fingerprint_detects_change():
    import jax.numpy as jnp

    from ml_trainer_tpu.parallel import check_desync, param_fingerprint

    tree = {"a": jnp.ones((4, 4)), "b": jnp.zeros((3,))}
    f1 = param_fingerprint(tree)
    tree2 = {"a": jnp.ones((4, 4)).at[0, 0].set(2.0), "b": jnp.zeros((3,))}
    assert param_fingerprint(tree2) != f1
    check_desync(tree)  # single-process: no-op


# ------------------------------------------------------- chunked LM loss
def test_chunked_lm_cross_entropy_matches_dense():
    import jax
    import jax.numpy as jnp
    import optax

    from ml_trainer_tpu.ops.losses import chunked_lm_cross_entropy

    rng = np.random.default_rng(0)
    b, s, d, v = 2, 64, 16, 97
    h = jnp.asarray(rng.normal(size=(b, s, d)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(v, d)), jnp.float32)
    t = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)

    def dense(h, emb):
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                h @ emb.T, t
            )
        )

    def chunked(h, emb):
        return chunked_lm_cross_entropy(h, emb, t, chunk_size=16)

    np.testing.assert_allclose(
        chunked(h, emb), dense(h, emb), rtol=1e-5
    )
    gc = jax.grad(chunked, argnums=(0, 1))(h, emb)
    gd = jax.grad(dense, argnums=(0, 1))(h, emb)
    for a, b_ in zip(gc, gd):
        np.testing.assert_allclose(a, b_, atol=1e-5, rtol=1e-4)
    with pytest.raises(ValueError, match="not divisible"):
        chunked_lm_cross_entropy(h, emb, t, chunk_size=60)


def test_gpt2_chunked_loss_trains_and_matches_dense_trajectory(tmp_path):
    """gpt2 with loss_chunk computes its own loss inside the forward (no
    [B,S,V] logits tensor); the training trajectory must match the dense
    criterion path on the same data/seed."""
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=3)

    def run(**model_kw):
        t = Trainer(
            get_model("gpt2_tiny", max_len=32, **model_kw),
            datasets=(ds, ds), epochs=2, batch_size=8,
            model_dir=str(tmp_path), optimizer="sgd", lr=0.1, metric=None,
        )
        t.fit()
        return t.train_losses + t.val_losses

    dense = run()
    chunked = run(loss_chunk=8)
    np.testing.assert_allclose(chunked, dense, rtol=2e-4)


def test_self_loss_model_rejects_metric(tmp_path):
    ds = SyntheticTokens(size=16, seq_len=32, vocab_size=256, seed=0)
    with pytest.raises(ValueError, match="metric must be None"):
        Trainer(
            get_model("gpt2_tiny", max_len=32, loss_chunk=8),
            datasets=(ds, ds), epochs=1, batch_size=8,
            model_dir=str(tmp_path), metric="accuracy",
        )


def test_foreign_self_loss_module_in_test_rejects_metric(tmp_path):
    """test() evaluates foreign modules; a self-loss module under a
    metric-bearing trainer must raise, not fabricate a 0.0 metric."""
    import jax

    ds = SyntheticTokens(size=16, seq_len=32, vocab_size=256, seed=0)
    host = Trainer(
        get_model("gpt2_tiny", max_len=32), datasets=(ds, ds), epochs=1,
        batch_size=8, model_dir=str(tmp_path), metric="accuracy",
    )
    foreign = get_model("gpt2_tiny", max_len=32, loss_chunk=8)
    variables = foreign.init(
        {"params": jax.random.PRNGKey(0)},
        np.zeros((1, 32), np.int32), train=False,
    )
    loader = Loader(ds, batch_size=8)
    with pytest.raises(ValueError, match="metric must be None"):
        host.test((foreign, variables), loader)
