"""Watchtower (telemetry/watchtower.py + alerts.py) — the fourth
observability pillar's contracts:

* TSDB: per-series rings are BOUNDED (oldest point evicted), the
  windowed arithmetic (``rate`` reset-aware, ``delta``, ``avg``,
  ``quantile_over_time`` via cumulative bucket deltas) matches hand
  computation, registry-sampled and exposition-ingested series share
  keys, and concurrent samplers/queriers never corrupt the store;
* alert engine: the level-rule state machine (pending -> firing after
  ``for_s``/``for_count`` -> resolved) on a fake clock, per-label-group
  evaluation, ``absent()`` rules, event-mode rules with action
  callbacks, and the engine instruments
  (``alert_active{rule=}`` / ``alerts_fired_total{rule=}``);
* watcher parity: the straggler watcher is a declarative event rule on
  the cluster engine — same counter/flight behavior PLUS alert history
  (the autoscaler/deploy re-expressions are pinned tick-by-tick by
  tests/test_overload.py and tests/test_deploy.py);
* dashboard: one self-contained HTML page (inline SVG sparklines, no
  assets), alert table included, hostile titles escaped;
* flight context: dumps carry the last-N trend of the allowlisted
  series;
* the trainer pin: a fit with telemetry on (which now samples the
  process store every log-sync) compiles NOTHING extra and yields the
  bit-identical trajectory, while the store actually fills;
* JSONL sink rotation: ``max_bytes`` rotates segments + sidecar index,
  and ``read_sink_records`` replays every segment in order;
* perf_diff (scripts/perf_diff.py): flatten/diff/categorize/format and
  the fastlane ``record_timing`` upsert.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

from ml_trainer_tpu.telemetry import MetricsRegistry, prometheus_text
from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
from ml_trainer_tpu.telemetry.flight import FlightRecorder
from ml_trainer_tpu.telemetry.watchtower import (
    TimeSeriesStore,
    bucket_quantile,
    install_flight_context,
    render_dashboard,
    watch_context,
)

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


# ---------------------------------------------------------------- TSDB


def test_ring_bounds_and_eviction():
    store = TimeSeriesStore(capacity=4)
    for i in range(10):
        store.append("g", float(i), t=float(i))
    points = store.last("g", n=10)
    assert len(points) == 4  # ring-bounded
    assert [v for _, v in points] == [6.0, 7.0, 8.0, 9.0]  # oldest out
    assert store.total_points() == 4
    assert len(store) == 1
    with pytest.raises(ValueError):
        TimeSeriesStore(capacity=1)  # can never answer a windowed query


def test_rate_delta_avg_hand_computed():
    store = TimeSeriesStore(capacity=64)
    # Counter with a restart at t=30: 0 -> 60 -> 90, then reset to 10.
    for t, v in [(0, 0.0), (10, 60.0), (20, 90.0), (30, 10.0)]:
        store.append("c_total", v, t=float(t))
    # Reset-aware increase: 60 + 30 + 10 = 100 over 30s.
    assert store.rate("c_total") == pytest.approx(100.0 / 30.0)
    # Windowed to the last 10s: only the reset sample's 10.
    assert store.rate("c_total", window_s=10.0, now=30.0) == (
        pytest.approx(1.0)
    )
    for t, v in [(0, 5.0), (10, 9.0), (20, 3.0)]:
        store.append("gauge", v, t=float(t))
    assert store.delta("gauge") == pytest.approx(-2.0)
    assert store.avg("gauge") == pytest.approx((5 + 9 + 3) / 3)
    assert store.minmax("gauge", max) == 9.0
    assert store.rate("lonely") is None  # absent series: no arithmetic
    store.append("lonely", 1.0, t=0.0)
    assert store.rate("lonely") is None  # <2 points


def test_quantile_over_time_hand_computed():
    r = MetricsRegistry()
    h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    store = TimeSeriesStore(capacity=64)
    store.sample_registry(r, t=0.0, force=True)  # empty baseline
    for v in [0.05, 0.05, 0.5, 0.5, 0.5, 0.5, 5.0, 5.0]:
        h.observe(v)
    store.sample_registry(r, t=10.0, force=True)
    # 8 observations in-window: q50 target=4 lands in (0.1, 1.0] with
    # cum 2 below it and 4 in-bucket -> 0.1 + 0.9 * (4-2)/4 = 0.55.
    q50 = store.quantile_over_time("lat_seconds", 0.5, window_s=60.0,
                                   now=10.0)
    assert q50 == pytest.approx(0.1 + 0.9 * 0.5)
    # q99 lands in +Inf? no: cum(10.0)=8 >= 7.92 -> interpolate in
    # (1.0, 10.0]: 1.0 + 9.0 * (7.92-6)/2 = 9.64.
    q99 = store.quantile_over_time("lat_seconds", 0.99, window_s=60.0,
                                   now=10.0)
    assert q99 == pytest.approx(1.0 + 9.0 * (7.92 - 6) / 2)
    # A second sweep with no new observations: the window [10, 20] has
    # zero increase -> None, not 0.0.
    store.sample_registry(r, t=20.0, force=True)
    assert store.quantile_over_time("lat_seconds", 0.5, window_s=9.0,
                                    now=20.0) is None
    # bucket_quantile direct: everything in the first bucket.
    assert bucket_quantile({0.5: 4.0, float("inf"): 4.0}, 0.5) == (
        pytest.approx(0.25)
    )


def test_sample_and_ingest_share_series_keys():
    r = MetricsRegistry()
    r.gauge("depth", labelnames=("tenant",)).labels(tenant="a").set(3.0)
    h = r.histogram("lat_seconds", buckets=(0.5, 2.0))
    h.observe(0.2)
    sampled = TimeSeriesStore(capacity=8)
    sampled.sample_registry(r, t=1.0, force=True)
    ingested = TimeSeriesStore(capacity=8)
    ingested.ingest_exposition(
        prometheus_text(r), t=1.0, extra_labels={"replica": "w0"},
        force=True,
    )
    assert ingested.last_value("depth", {"tenant": "a"}) == 3.0
    # The merged federation label is queryable...
    assert ingested.last_value(
        "depth", {"tenant": "a", "replica": "w0"}
    ) == 3.0
    # ...and bucket keys line up between the two ingestion paths.
    for store, extra in ((sampled, {}), (ingested, {"replica": "w0"})):
        assert store.last_value(
            "lat_seconds_bucket", dict(extra, le="0.5")
        ) == 1.0
        assert store.last_value(
            "lat_seconds_bucket", dict(extra, le="+Inf")
        ) == 1.0
    # Ambiguous selections raise instead of silently picking one.
    r.gauge("depth", labelnames=("tenant",)).labels(tenant="b").set(4.0)
    sampled.sample_registry(r, t=2.0, force=True)
    with pytest.raises(ValueError):
        sampled.last_value("depth")


def test_concurrent_sample_vs_query_hammer():
    r = MetricsRegistry()
    g = r.gauge("hot", labelnames=("i",))
    c = r.counter("hits_total")
    store = TimeSeriesStore(capacity=32)
    stop = threading.Event()
    errors = []

    def writer():
        t = 0.0
        while not stop.is_set():
            for i in range(8):
                g.labels(i=str(i)).set(float(i))
            c.inc()
            store.sample_registry(r, t=t, force=True)
            t += 1.0

    def reader():
        while not stop.is_set():
            try:
                store.names()
                store.select("hot")
                store.rate("hits_total")
                store.dump()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader) for _ in range(3)
    ]
    for t in threads:
        t.start()
    import time as _time

    _time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors
    assert all(
        len(points) <= 32 for _, points in store.select("hot")
    )


def test_dump_load_roundtrip_exact(tmp_path):
    store = TimeSeriesStore(capacity=8)
    store.append("a", 1.5, {"x": "1"}, t=1.0)
    store.append("a", 2.5, {"x": "1"}, t=2.0)
    store.append("b", -3.0, t=2.0)
    path = store.save(str(tmp_path / "wt.json"))
    loaded = TimeSeriesStore.load(json.load(open(path)))
    assert loaded.dump() == store.dump()


# ---------------------------------------------------------------- alerts


def test_level_rule_state_machine_fake_clock():
    clock = [100.0]
    store = TimeSeriesStore(capacity=32)
    registry = MetricsRegistry()
    flight = FlightRecorder()
    engine = AlertEngine(
        store=store, registry=registry, flight=flight,
        clock=lambda: clock[0],
    )
    engine.add_rule(AlertRule(
        "hot_gauge", "pressure > 0.8", for_s=10.0, severity="warn",
    ))
    store.append("pressure", 0.5, t=clock[0])
    assert engine.evaluate() == []  # below threshold: nothing
    store.append("pressure", 0.9, t=clock[0])
    assert engine.evaluate() == []  # pending: breach younger than for_s
    assert not engine.rule("hot_gauge").firing()
    clock[0] += 11.0
    store.append("pressure", 0.95, t=clock[0])
    events = engine.evaluate()
    assert [e["state"] for e in events] == ["firing"]
    assert engine.rule("hot_gauge").firing()
    assert events[0]["value"] == 0.95
    # Instruments + flight took the one firing path.
    snap = registry.snapshot()
    assert snap["alerts_fired_total{rule=hot_gauge}"] == 1
    assert snap["alert_active{rule=hot_gauge}"] == 1.0
    assert [rec["rule"] for rec in flight.records()
            if rec["kind"] == "alert"] == ["hot_gauge"]
    # Still firing: no duplicate event, the streak just holds.
    clock[0] += 5.0
    store.append("pressure", 0.99, t=clock[0])
    assert engine.evaluate() == []
    # Recovery resolves exactly once.
    clock[0] += 5.0
    store.append("pressure", 0.1, t=clock[0])
    events = engine.evaluate()
    assert [e["state"] for e in events] == ["resolved"]
    assert not engine.rule("hot_gauge").firing()
    assert registry.snapshot()["alert_active{rule=hot_gauge}"] == 0.0
    assert [e["state"] for e in engine.history()
            if e["rule"] == "hot_gauge"] == ["firing", "resolved"]


def test_per_label_group_evaluation():
    clock = [0.0]
    store = TimeSeriesStore(capacity=32)
    engine = AlertEngine(store=store, clock=lambda: clock[0])
    engine.add_rule(AlertRule("deep", "queue_depth > 5"))
    store.append("queue_depth", 9.0, {"tenant": "a"}, t=0.0)
    store.append("queue_depth", 1.0, {"tenant": "b"}, t=0.0)
    events = engine.evaluate()
    assert [e["labels"] for e in events] == [{"tenant": "a"}]
    assert engine.rule("deep").firing({"tenant": "a"})
    assert not engine.rule("deep").firing({"tenant": "b"})
    assert engine.rule("deep").n_firing() == 1


def test_absent_series_rule():
    clock = [0.0]
    store = TimeSeriesStore(capacity=8)
    engine = AlertEngine(store=store, clock=lambda: clock[0])
    engine.add_rule(AlertRule(
        "no_heartbeat", "absent(train_goodput_fraction)",
        severity="warn",
    ))
    events = engine.evaluate()
    assert [e["state"] for e in events] == ["firing"]
    store.append("train_goodput_fraction", 0.9, t=0.0)
    events = engine.evaluate()
    assert [e["state"] for e in events] == ["resolved"]


def test_event_mode_rule_runs_actions_with_extra():
    seen = []
    engine = AlertEngine(clock=lambda: 0.0)
    engine.add_rule(AlertRule(
        "tick", mode="event", actions=(seen.append,),
    ))
    assert engine.observe("tick", True, value=2.0,
                          extra={"host": 3}) is True
    assert engine.observe("tick", False) is False
    assert engine.observe("tick", True, value=4.0,
                          extra={"host": 3}) is True
    assert [e["value"] for e in seen] == [2.0, 4.0]  # re-fires per event
    assert all(e["host"] == 3 and e["state"] == "event" for e in seen)


def test_expr_rate_and_quantile_predicates():
    clock = [60.0]
    store = TimeSeriesStore(capacity=32)
    engine = AlertEngine(store=store, clock=lambda: clock[0])
    engine.add_rule(AlertRule("errs", "rate(errors_total[60s]) > 0.5"))
    store.append("errors_total", 0.0, t=0.0)
    store.append("errors_total", 60.0, t=60.0)  # 1/s
    assert [e["rule"] for e in engine.evaluate()] == ["errs"]
    r = MetricsRegistry()
    h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
    store2 = TimeSeriesStore(capacity=32)
    engine2 = AlertEngine(store=store2, clock=lambda: clock[0])
    engine2.add_rule(AlertRule(
        "slow", "quantile(0.5, lat_seconds[120s]) > 0.5"))
    store2.sample_registry(r, t=0.0, force=True)
    for _ in range(4):
        h.observe(0.9)
    store2.sample_registry(r, t=60.0, force=True)
    assert [e["rule"] for e in engine2.evaluate()] == ["slow"]


# ------------------------------------------------------- watcher parity


def test_straggler_watcher_is_declarative_event_rule():
    """PR 20 re-expression: the cluster straggler detector routes
    through the alert engine — legacy counter/flight/hook behavior
    intact (pinned by test_telemetry.py) PLUS the alert record."""
    from ml_trainer_tpu.telemetry import ClusterTelemetry, HEARTBEAT_FIELDS

    r = MetricsRegistry()
    fr = FlightRecorder()
    ct = ClusterTelemetry(registry=r, flight=fr, straggler_factor=2.0)
    rule = ct.alerts.rule("cluster_straggler")
    assert rule.mode == "event" and rule.severity == "warn"
    f = len(HEARTBEAT_FIELDS)
    i50 = HEARTBEAT_FIELDS.index("step_ms_p50")
    skewed = np.zeros((2, f))
    skewed[:, i50] = (10.0, 25.0)
    ct._ingest(skewed, step=7)
    # Legacy side effects still fire (the rule's action)...
    assert r.snapshot()["cluster_straggler_events_total{host=1}"] == 1
    legacy = [rec for rec in fr.records() if rec["kind"] == "straggler"]
    assert legacy and legacy[-1]["host"] == 1
    # ...and the ONE alerting path now also records it.
    alerts = [rec for rec in fr.records() if rec["kind"] == "alert"]
    assert alerts and alerts[-1]["rule"] == "cluster_straggler"
    assert alerts[-1]["labels"] == {"host": "1"}
    hist = [e for e in ct.alerts.history()
            if e["rule"] == "cluster_straggler"]
    assert hist and hist[-1]["factor"] == 2.5


def test_autoscaler_rules_live_on_router_engine():
    """The autoscaler registers its hysteresis watchers as named rules
    on the shared engine (tick-by-tick parity is pinned by
    tests/test_overload.py)."""
    from ml_trainer_tpu.serving.autoscaler import (
        Autoscaler, AutoscalerConfig,
    )

    class _Router:
        alerts = AlertEngine(clock=lambda: 0.0)
        ladder = None

        def fleet_slo_snapshot(self):
            return {"burn": None, "window_requests": 0, "now": 0.0}

    sc = Autoscaler(_Router(), None,
                    config=AutoscalerConfig(high_polls=3, low_polls=2))
    assert sc.alerts is _Router.alerts
    assert sc.alerts.rule("autoscaler_burn_high").for_count == 3
    assert sc.alerts.rule("autoscaler_burn_low").for_count == 2


# ------------------------------------------------------------ dashboard


def test_dashboard_golden_shape():
    store = TimeSeriesStore(capacity=16)
    for t in range(6):
        store.append("train_goodput_fraction", 0.8 + t / 100,
                     t=float(t))
    store.append("lat_seconds_bucket", 1.0, {"le": "0.5"}, t=0.0)
    alerts = [{
        "t": 3.0, "rule": "hot_gauge", "severity": "page",
        "state": "firing", "value": 0.97, "labels": {"tenant": "a"},
    }]
    html = render_dashboard(
        store, title='<run "7">', alerts=alerts,
    )
    assert html.startswith("<!doctype html>")
    assert "&lt;run &quot;7&quot;&gt;" in html  # hostile title escaped
    assert "train_goodput_fraction" in html
    assert "<polyline points=" in html  # inline sparkline, no assets
    assert 'class="state-firing"' in html and "hot_gauge" in html
    assert "lat_seconds_bucket" not in html  # buckets folded away
    assert "http://" not in html and "src=" not in html


def test_flight_context_carries_trend():
    store = TimeSeriesStore(capacity=64)
    for t in range(40):
        store.append("train_goodput_fraction", t / 40, t=float(t))
    store.append("unrelated_gauge", 1.0, t=0.0)
    ctx = watch_context(store, n=32)
    assert list(ctx) == ["train_goodput_fraction"]
    assert len(ctx["train_goodput_fraction"]) == 32  # last-N only
    fr = FlightRecorder()
    install_flight_context(store=store, recorder=fr)
    fr.record("step", step=1)
    dump = fr.payload(reason="unit")
    assert "watchtower" in dump.get("context", {})


# ----------------------------------------------- trainer pin (slow-ish)


def test_trainer_fit_fills_store_zero_extra_compiles(tmp_path):
    """Watchtower ON changes nothing the step computes: same compile
    count as the bare fit, bit-identical params — while the process
    store actually accumulates trainer series at the log-sync cadence."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.telemetry import compile_watch
    from ml_trainer_tpu.telemetry.watchtower import (
        default_store, reset_default_store,
    )
    from ml_trainer_tpu.utils.functions import custom_pre_process_function
    import jax

    def make(model_dir, **kw):
        t = custom_pre_process_function()
        return Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0, transform=t),
                      SyntheticCIFAR10(size=32, seed=1, transform=t)),
            epochs=1, batch_size=16, model_dir=str(model_dir),
            metric=None, lr=0.01, **kw,
        )

    compile_watch.install()
    pw_before = compile_watch.post_warmup_count()
    bare = make(tmp_path / "bare")
    bare.fit()
    reset_default_store()
    try:
        instr = make(tmp_path / "instr", telemetry=True)
        instr.fit()
        store = default_store()
        assert store.last_value("train_goodput_fraction") is not None
        assert store.total_points() > 0
    finally:
        reset_default_store()
    assert compile_watch.post_warmup_count() == pw_before
    for a, b in zip(
        jax.tree.leaves(bare.state.params),
        jax.tree.leaves(instr.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------ sink rotation


def test_jsonl_sink_rotation_and_replay(tmp_path):
    from ml_trainer_tpu.telemetry.export import (
        JsonlSink, read_sink_records,
    )

    path = str(tmp_path / "metrics.jsonl")
    sink = JsonlSink(path, max_bytes=400)
    for i in range(40):
        sink.write({"i": i, "pad": "x" * 24})
    sink.close()
    idx = json.load(open(path + ".index.json"))
    assert len(idx["rotated"]) >= 2  # it DID rotate
    for seg in idx["rotated"]:
        assert os.path.exists(seg["path"])
        assert os.path.getsize(seg["path"]) <= 400 + 200  # record slop
    # Replay covers every segment, in write order, live tail last.
    records = read_sink_records(path)
    assert [rec["i"] for rec in records] == list(range(40))
    # A re-opened sink resumes the segment counter (no overwrite).
    sink2 = JsonlSink(path, max_bytes=400)
    for i in range(40, 60):
        sink2.write({"i": i, "pad": "x" * 24})
    sink2.close()
    records = read_sink_records(path)
    assert [rec["i"] for rec in records] == list(range(60))


# -------------------------------------------------------- perf_diff


@pytest.fixture()
def perf_diff():
    sys.path.insert(0, SCRIPTS)
    try:
        import perf_diff as mod

        yield mod
    finally:
        sys.path.remove(SCRIPTS)


def test_perf_diff_flatten_and_attribution(perf_diff):
    old = {
        "decode_tokens_per_sec": 100.0,
        "legs": [{"name": "serve", "p99_ms": 20.0}],
        "compile_events_post_warmup_total": 0,
        "written_at": 111.0,
    }
    new = {
        "decode_tokens_per_sec": 80.0,
        "legs": [{"name": "serve", "p99_ms": 30.0}],
        "compile_events_post_warmup_total": 2,
        "written_at": 999.0,  # timestamp churn must not show up
        "kv_pages_free": 5,
    }
    rows = perf_diff.diff_leaves(
        perf_diff.flatten(old), perf_diff.flatten(new)
    )
    by_key = {r["key"]: r for r in rows}
    assert "written_at" not in by_key
    assert by_key["decode_tokens_per_sec"]["pct"] == pytest.approx(20.0)
    assert by_key["decode_tokens_per_sec"]["category"] == "throughput"
    assert by_key["legs[serve].p99_ms"]["category"] == "latency"
    assert by_key["compile_events_post_warmup_total"]["category"] == (
        "compiles"
    )
    assert by_key["kv_pages_free"]["note"] == "appeared"
    table = perf_diff.format_table(rows, top=10)
    assert "legs[serve].p99_ms" in table
    assert "changed leaves" in table  # the per-ledger rollup line


def test_perf_diff_reads_tsdb_dumps(perf_diff, tmp_path):
    a, b = TimeSeriesStore(capacity=8), TimeSeriesStore(capacity=8)
    for store, v in ((a, 10.0), (b, 40.0)):
        store.append("queue_depth", 1.0, {"tenant": "x"}, t=0.0)
        store.append("queue_depth", v, {"tenant": "x"}, t=5.0)
    pa = a.save(str(tmp_path / "a.json"))
    pb = b.save(str(tmp_path / "b.json"))
    rows = perf_diff.diff_files(pa, pb)
    assert [r["key"] for r in rows] == ["queue_depth{tenant=x}"]
    assert rows[0]["old"] == 10.0 and rows[0]["new"] == 40.0


def test_perf_diff_record_timing_upserts(perf_diff, tmp_path):
    path = str(tmp_path / "timings.json")
    perf_diff.record_timing(path, "serving", 40.0, rc=0)
    payload = perf_diff.record_timing(path, "watchtower", 12.5, rc=0)
    assert payload["total_seconds"] == pytest.approx(52.5)
    payload = perf_diff.record_timing(path, "serving", 38.0, rc=1)
    on_disk = json.load(open(path))
    assert on_disk["legs"]["serving"] == payload["legs"]["serving"]
    assert on_disk["legs"]["serving"]["seconds"] == 38.0  # upserted
    assert on_disk["legs"]["serving"]["rc"] == 1
    assert on_disk["total_seconds"] == pytest.approx(50.5)
