"""Config system tests — whitelist enforcement and defaults parity
(ref: src/trainer.py:26-41, 307-311)."""

import pytest

from ml_trainer_tpu.config import ALLOWED_KWARGS, TrainerConfig, validate_kwargs


def test_defaults_match_reference():
    cfg = TrainerConfig.from_kwargs()
    assert cfg.seed == 32
    assert cfg.scheduler is None
    assert cfg.optimizer == "sgd"
    assert cfg.momentum == 0.9
    assert cfg.weight_decay == 0.0
    assert cfg.lr == 0.001
    assert cfg.criterion == "cross_entropy"
    assert cfg.metric == "accuracy"
    assert cfg.pred_function == "softmax"
    assert cfg.model_dir == "model_output"


def test_whitelist_is_reference_eleven_keys():
    assert ALLOWED_KWARGS == {
        "seed", "scheduler", "optimizer", "momentum", "weight_decay",
        "lr", "criterion", "metric", "pred_function", "model_dir", "backend",
    }


def test_unknown_kwarg_raises_typeerror():
    with pytest.raises(TypeError):
        TrainerConfig.from_kwargs(epochs=5)
    with pytest.raises(TypeError):
        validate_kwargs({"nope": 1}, ALLOWED_KWARGS)


def test_backend_aliases_map_to_tpu_native():
    assert TrainerConfig.from_kwargs(backend="smddp").backend == "tpu"
    assert TrainerConfig.from_kwargs(backend="nccl").backend == "tpu"
    assert TrainerConfig.from_kwargs(backend="gloo").backend == "cpu"


def test_version_matches_pyproject():
    """__version__ and pyproject.toml must move in lockstep (they had
    silently diverged once)."""
    import os
    import re

    import ml_trainer_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "pyproject.toml")) as fp:
        m = re.search(r'^version = "([^"]+)"', fp.read(), re.M)
    assert m and m.group(1) == ml_trainer_tpu.__version__
