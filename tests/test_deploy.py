"""Live base-model rollout (serving/deploy.py, docs/serving.md
"Deploys") + the weights-fingerprint KV-portability gate
(serving/transfer.py, checkpoint/).

The pins: a healthy deploy ramps canary -> 100% and promotes — after
which the router serves the NEW weights byte-identically to
``generate()`` on them; a forced-regression canary (wedged new-gen
replicas) auto-rolls-back within one poll window with zero dropped
streams and byte-identical output on the stable fleet; KV never
migrates across weights (``WeightsMismatch``, keyed on the checkpoint
manifest's fingerprint); shadow mode diffs outputs before any real
traffic moves.  The train-to-serve loop closes with
``Trainer.fit() -> save_model -> Router.deploy``.
"""

import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.checkpoint import (
    load_model_manifest,
    weights_fingerprint,
    weights_structure_digest,
    write_model_manifest,
)
from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.serving import (
    DeployConfig,
    Deployment,
    Router,
    Server,
    WeightsMismatch,
    transfer,
)
from ml_trainer_tpu.serving.deploy import TERMINAL_STATES
from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.scheduler import Request
from ml_trainer_tpu.serving.slo import SloPolicy

PS = 8
VOCAB = 256  # small vocab keeps in-process compiles cheap


@pytest.fixture(scope="module")
def model_and_two_weights():
    """One architecture, two weight sets — generations 0 and 1."""
    model = get_model("gpt2_tiny", vocab_size=VOCAB, max_len=64)
    x = np.zeros((1, 8), np.int32)
    v0 = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    v1 = model.init({"params": jax.random.PRNGKey(1)}, x, train=False)
    return model, v0, v1


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, n), np.int32
    )


def _tenants(fraction, canary, n=6):
    """The first ``n`` tenant names whose deterministic slice falls
    inside (canary=True) / outside the ``[0, fraction)`` split."""
    out = []
    i = 0
    while len(out) < n:
        t = f"tenant{i}"
        if (Router.tenant_slice(t) < fraction) == canary:
            out.append(t)
        i += 1
    return out


# ------------------------------------------------ weights fingerprint


def test_fingerprint_distinguishes_weights_not_structure(
        model_and_two_weights):
    _, v0, v1 = model_and_two_weights
    assert weights_fingerprint(v0) != weights_fingerprint(v1)
    assert weights_structure_digest(v0) == weights_structure_digest(v1)
    # Deterministic: same tree, same digest, every call.
    assert weights_fingerprint(v0) == weights_fingerprint(v0)
    assert weights_fingerprint(v0).startswith("w:")
    assert weights_structure_digest(v0).startswith("cfg:")


def test_model_manifest_records_fingerprint(tmp_path,
                                            model_and_two_weights):
    _, v0, _ = model_and_two_weights
    meta = write_model_manifest(str(tmp_path), v0)
    loaded = load_model_manifest(str(tmp_path))
    assert loaded == meta
    assert loaded["weights_fingerprint"] == weights_fingerprint(v0)
    assert loaded["structure_digest"] == weights_structure_digest(v0)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert load_model_manifest(str(empty)) is None  # pre-manifest export


def test_kv_import_refuses_cross_weights(model_and_two_weights):
    """The KV-portability rule: a slot exported under one weights
    fingerprint must never be adopted by an engine serving another —
    structured ``weights_mismatch`` refusal, not silent garbage."""
    model, v0, v1 = model_and_two_weights
    e0 = SlotDecodeEngine(model, v0, max_batch=2, kv_page_size=PS)
    e1 = SlotDecodeEngine(model, v1, max_batch=2, kv_page_size=PS)
    assert e0.weights_fp != e1.weights_fp
    assert e0.weights_fp == weights_fingerprint({"params": v0["params"]})

    req = Request(prompt=_prompt(0, 9), max_new_tokens=12)
    assert e0.admit(req, 0) == "active"
    for _ in range(4):
        e0.step()
    exp = transfer.export_kv_slot(e0, 0)
    assert exp.weights_fp == e0.weights_fp

    cont = Request(prompt=req.prompt, max_new_tokens=12)
    cont.tokens = list(req.tokens)
    with pytest.raises(WeightsMismatch, match="weights_mismatch"):
        transfer.import_kv_slot(e1, cont, 1, exp)
    # Same weights (a FRESH engine on v0): adoption proceeds.
    e0b = SlotDecodeEngine(model, v0, max_batch=2, kv_page_size=PS)
    assert transfer.import_kv_slot(e0b, cont, 1, exp) == "active"


def test_transfer_bytes_carry_weights_fp(model_and_two_weights):
    model, v0, _ = model_and_two_weights
    eng = SlotDecodeEngine(model, v0, max_batch=2, kv_page_size=PS)
    req = Request(prompt=_prompt(1, 8), max_new_tokens=8)
    assert eng.admit(req, 0) == "active"
    eng.step()
    exp = transfer.export_kv_slot(eng, 0)
    back = transfer.from_bytes(transfer.to_bytes(exp))
    assert back.weights_fp == exp.weights_fp == eng.weights_fp


# ------------------------------------------------ deterministic split


def test_tenant_slice_is_deterministic_and_bounded():
    seen = [Router.tenant_slice(f"t{i}") for i in range(512)]
    assert all(0.0 <= s < 1.0 for s in seen)
    assert seen == [Router.tenant_slice(f"t{i}") for i in range(512)]
    # Roughly uniform: a 25% split captures SOME but not all tenants.
    inside = sum(1 for s in seen if s < 0.25)
    assert 0 < inside < len(seen)


def test_generation_split_routes_canary_cohort(model_and_two_weights):
    """With a split active, canary-slice tenants place on the new
    generation and everyone else stays on stable — per placement, not
    per coin flip."""
    model, v0, v1 = model_and_two_weights
    with Router.build(model, v0, roles=["both"], max_batch=2,
                      kv_page_size=PS,
                      router_kwargs=dict(hedging=False)) as router:
        new_server = Server(model, v1, max_batch=2, kv_page_size=PS,
                            role="both")
        router.add_replica("deploy1-both0", new_server, generation=1)
        router.set_deploy_split(1, 0.25)
        canary_t = _tenants(0.25, True, n=2)
        stable_t = _tenants(0.25, False, n=2)
        p = _prompt(2, 8)
        ref0 = np.asarray(generate(model, v0, p[None], 6))[0]
        ref1 = np.asarray(generate(model, v1, p[None], 6))[0]
        for t in canary_t:
            np.testing.assert_array_equal(
                router.complete(p, 6, timeout=180, tenant=t), ref1
            )
        for t in stable_t:
            np.testing.assert_array_equal(
                router.complete(p, 6, timeout=180, tenant=t), ref0
            )
        counts = router.snapshot()["requests_total"]
    assert counts.get("colocated/deploy1-both0") == len(canary_t)
    assert counts.get("colocated/rep0") == len(stable_t)


# ------------------------------------------------------- deployments


def _deploy_router(model, variables, **slo_kw):
    policy = SloPolicy(**{**dict(ttft_ms=60_000.0, tpot_ms=60_000.0,
                                 target=0.9), **slo_kw})
    return Router.build(
        model, variables, roles=["both", "both"], max_batch=2,
        kv_page_size=PS,
        router_kwargs=dict(hedging=False, slo=policy),
    )


def _server_factory(model, variables, wedge_s=0.0):
    def factory(role):
        server = Server(model, variables, max_batch=2, kv_page_size=PS,
                        role=role)
        if wedge_s:
            inner = server.submit_request

            def wedged(req, _inner=inner):
                time.sleep(wedge_s)
                _inner(req)

            server.submit_request = wedged
        return server

    return factory


def test_deploy_ramps_and_promotes(model_and_two_weights):
    """Healthy rollout: staging spawns a full new generation, traffic
    walks canary -> 100%, the new generation is promoted and the old
    one retires — and the fleet then serves the new weights
    byte-identically to generate() on them."""
    model, v0, v1 = model_and_two_weights
    p = _prompt(3, 8)
    ref1 = np.asarray(generate(model, v1, p[None], 6))[0]
    cfg = DeployConfig(canary=0.25, stages=(1.0,), hold_s=0.05,
                       min_window_requests=1, drain_timeout_s=30.0)
    with _deploy_router(model, v0) as router:
        router.complete(p, 4, timeout=180)  # warm the stable fleet
        dep = Deployment(router, "ckpt-v1",
                         _server_factory(model, v1), config=cfg)
        assert dep.tick() == "canary"
        assert router._deploy_generation == 1
        assert router._deploy_fraction == pytest.approx(0.25)
        assert len(dep.new_replicas) == 2  # mirrors the stable role mix
        assert dep.weights_fp != dep.old_weights_fp
        for t in _tenants(0.25, True, n=2):
            router.complete(p, 6, timeout=180, tenant=t)
        time.sleep(cfg.hold_s + 0.01)
        assert dep.tick() == "ramping"
        assert router._deploy_fraction == pytest.approx(1.0)
        time.sleep(cfg.hold_s + 0.01)
        assert dep.tick() == "done"
        # Promoted: default traffic serves the new weights...
        assert router._serving_generation == 1
        assert router._deploy_generation is None
        np.testing.assert_array_equal(
            router.complete(p, 6, timeout=180), ref1
        )
        # ...and the old generation is fully retired.
        assert set(router.replicas) == set(dep.new_replicas)
        actions = [e["action"] for e in dep.events]
    assert "staged" in actions and "promoted" in actions
    assert dep.report()["state"] == "done"


def test_stage_min_requests_holds_until_slice_reports(
        model_and_two_weights):
    """With ``stage_min_requests`` set, a stage may NOT advance on the
    hold timer alone: the canary window must report finished requests
    first, so a slice whose requests are all still in flight (a slow
    regression) cannot outrun the watch."""
    model, v0, v1 = model_and_two_weights
    p = _prompt(9, 8)
    cfg = DeployConfig(canary=0.25, stages=(1.0,), hold_s=0.0,
                       min_window_requests=1, stage_min_requests=1)
    with _deploy_router(model, v0) as router:
        dep = Deployment(router, "ckpt-v1",
                         _server_factory(model, v1), config=cfg)
        assert dep.tick() == "canary"
        # Hold expired, but the slice has not reported: no advance.
        assert dep.tick() == "canary"
        assert dep.tick() == "canary"
        router.complete(p, 4, timeout=180,
                        tenant=_tenants(0.25, True, n=1)[0])
        assert dep.tick() == "ramping"  # the slice reported: advance
        assert dep.tick() == "done"     # window still holds the report
        assert router._serving_generation == 1


def test_forced_regression_canary_rolls_back(model_and_two_weights):
    """The satellite pin: wedge ONLY the canary (new-generation)
    replicas; the canary slice's burn trips the threshold and the
    deployment rolls back within one poll — zero dropped streams,
    stable-fleet output byte-identical throughout, split torn down."""
    model, v0, v1 = model_and_two_weights
    p = _prompt(4, 8)
    ref0 = np.asarray(generate(model, v0, p[None], 6))[0]
    cfg = DeployConfig(canary=0.25, stages=(1.0,), hold_s=60.0,
                       burn_threshold=2.0, high_polls=1,
                       min_window_requests=2, drain_timeout_s=60.0)
    with _deploy_router(model, v0, ttft_ms=250.0) as router:
        for t in _tenants(0.25, False, n=2):  # warm stable, pre-split
            router.complete(p, 4, timeout=180, tenant=t)
        dep = Deployment(router, "ckpt-wedged",
                         _server_factory(model, v1, wedge_s=0.6),
                         config=cfg)
        assert dep.tick() == "canary"
        canary_t = _tenants(0.25, True, n=3)
        stable_t = _tenants(0.25, False, n=3)
        stable_streams = [
            router.submit(p, 6, tenant=t) for t in stable_t
        ]
        canary_streams = [
            router.submit(p, 6, tenant=t) for t in canary_t
        ]
        canary_out = [s.result(timeout=180) for s in canary_streams]
        # One more canary stream still in flight when rollback fires:
        # it must drain or redistribute, never drop.
        inflight = router.submit(p, 6, tenant=canary_t[0])
        assert dep.tick() == "rolled_back"  # one poll, not a window
        assert dep.last_burn >= cfg.burn_threshold
        assert "canary burn" in dep.rollback_cause
        # Split torn down, new generation drained out of the fleet.
        assert router._deploy_generation is None
        assert router._deploy_fraction == 0.0
        assert set(router.replicas) == {"rep0", "rep1"}
        # Zero dropped streams: everything in flight completed.
        assert np.asarray(inflight.result(timeout=180)).size > 0
        for s, out in zip(stable_streams,
                          (s.result(timeout=180) for s in stable_streams)):
            np.testing.assert_array_equal(out, ref0)
        assert all(np.asarray(o).size > 0 for o in canary_out)
        # And the stable fleet still serves byte-identical output.
        np.testing.assert_array_equal(
            router.complete(p, 6, timeout=180, tenant=stable_t[0]), ref0
        )
    assert dep.report()["state"] == "rolled_back"


def test_shadow_mismatch_rolls_back_before_traffic_moves(
        model_and_two_weights):
    """Shadow mode replays live requests against the new weights OFF
    the serving path; different tokens -> rollback with the traffic
    split never having been raised."""
    model, v0, v1 = model_and_two_weights
    cfg = DeployConfig(shadow=True, shadow_fraction=1.0,
                       shadow_min_requests=1)
    with _deploy_router(model, v0) as router:
        dep = Deployment(router, "ckpt-diff",
                         _server_factory(model, v1), config=cfg)
        assert dep.tick() == "shadowing"
        assert router._request_tap is not None
        router.complete(_prompt(5, 8), 6, timeout=180, tenant="live")
        assert dep.tick() == "rolled_back"
        report = dep.shadow_report()
        assert report["n_token_mismatch"] >= 1
        assert "shadow diff" in dep.rollback_cause
        # No real traffic ever moved: no stage event, split never set.
        assert all(e["action"] != "stage" for e in dep.events)
        assert router._deploy_fraction == 0.0
        assert router._request_tap is None


def test_shadow_clean_proceeds_to_canary(model_and_two_weights):
    """Same weights shadow-side: replayed tokens match, latency is
    diffed into the report, and the rollout proceeds to canary."""
    model, v0, _ = model_and_two_weights
    cfg = DeployConfig(shadow=True, shadow_fraction=1.0,
                       shadow_min_requests=1, canary=0.25,
                       min_window_requests=10_000)
    with _deploy_router(model, v0) as router:
        dep = Deployment(router, "ckpt-same",
                         _server_factory(model, v0), config=cfg)
        assert dep.tick() == "shadowing"
        router.complete(_prompt(6, 8), 6, timeout=180, tenant="live")
        assert dep.tick() == "canary"
        report = dep.shadow_report()
        assert report["n_compared"] >= 1
        assert report["n_token_mismatch"] == 0
        assert report["shadow_e2e_ms_p50"] is not None
        assert router._deploy_fraction == pytest.approx(0.25)
        dep.close()


def test_deploy_guards(model_and_two_weights):
    model, v0, _ = model_and_two_weights
    with _deploy_router(model, v0) as router:
        with pytest.raises(ValueError, match="factory"):
            router.deploy("some-ckpt")  # no fleet, no factory
        dep = Deployment(router, "x", _server_factory(model, v0))
        router._deployment = dep  # unfinished: a second deploy refuses
        assert not dep.finished() and dep.state not in TERMINAL_STATES
        with pytest.raises(RuntimeError, match="already"):
            router.deploy("y", factory=_server_factory(model, v0))
        router._deployment = None


def test_deploy_flight_events_and_gauges(model_and_two_weights):
    model, v0, v1 = model_and_two_weights
    from ml_trainer_tpu.telemetry.flight import get_recorder
    from ml_trainer_tpu.telemetry.registry import default_registry

    cfg = DeployConfig(canary=0.25, stages=(1.0,), hold_s=0.0,
                       min_window_requests=10_000)
    with _deploy_router(model, v0) as router:
        dep = Deployment(router, "ckpt-v1",
                         _server_factory(model, v1), config=cfg)
        while not dep.finished():
            dep.tick()
        assert dep.state == "done"
    rows = [r for r in get_recorder().records() if r["kind"] == "deploy"]
    assert any(r.get("action") == "transition" and r.get("to") == "done"
               for r in rows)
    assert any(r.get("action") == "stage" for r in rows)
    snap = default_registry().snapshot()
    assert snap["serving_deploy_state{state=done}"] == 1.0
    assert snap["serving_deploy_generation"] == 1.0
    assert snap["serving_deploy_fraction"] == 0.0  # promoted: split down


# --------------------------------------- autoscaler stderr post-mortem


def test_replace_dead_attaches_stderr_tail(model_and_two_weights,
                                           tmp_path):
    """Satellite pin: a worker that dies AFTER readiness loses its
    stderr — the autoscaler's replace-dead flight event carries a
    bounded tail of the dead process's log instead."""
    from ml_trainer_tpu.serving import Autoscaler, AutoscalerConfig

    model, v0, _ = model_and_two_weights

    class _DeadProc:
        returncode = -9

        def poll(self):
            return -9

    with _deploy_router(model, v0) as router:
        rep = router.replica("rep0")
        rep.healthy = False
        rep.server.proc = _DeadProc()
        rep.server.stderr_tail = (
            lambda max_bytes=2048: "boom: fake traceback tail\n"
        )
        auto = Autoscaler(
            router, _server_factory(model, v0),
            config=AutoscalerConfig(min_replicas=3),
        )
        assert auto._scale_up("both", "replica rep0 found dead",
                              auto._clock(), repair=True)
        action = auto.actions[-1]
    assert action["action"] == "scale_up"
    assert "boom: fake traceback tail" in action["dead_stderr"]["rep0"]


# --------------------------------------------- train -> export -> deploy


@pytest.mark.slow
def test_trainer_fit_export_deploy_loop(tmp_path):
    """The full loop: fit a tiny gpt2, export (manifest + fingerprint),
    deploy the export onto a live in-process fleet serving the seed
    init, and verify the promoted fleet serves the TRAINED weights
    byte-identically to generate() on the loaded export."""
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.checkpoint import load_model_variables
    from ml_trainer_tpu.data import SyntheticTokens

    model = get_model("gpt2_tiny", vocab_size=VOCAB, max_len=64)
    ds = SyntheticTokens(size=32, seq_len=16, vocab_size=VOCAB, seed=0)
    trainer = Trainer(
        model, datasets=(ds, ds), epochs=1, batch_size=8, metric=None,
        model_dir=str(tmp_path), seed=7, lr=0.01,
    )
    trainer.fit()
    manifest = load_model_manifest(str(tmp_path))
    assert manifest and manifest["weights_fingerprint"].startswith("w:")

    trained = load_model_variables(str(tmp_path))
    p = _prompt(7, 8)
    ref = np.asarray(generate(model, trained, p[None], 6))[0]
    x = np.zeros((1, 8), np.int32)
    seed_vars = model.init(
        {"params": jax.random.PRNGKey(0)}, x, train=False
    )
    cfg = DeployConfig(canary=0.25, stages=(1.0,), hold_s=0.0,
                       min_window_requests=10_000)

    def factory(role):
        return Server(model, load_model_variables(str(tmp_path)),
                      max_batch=2, kv_page_size=PS, role=role)

    with _deploy_router(model, seed_vars) as router:
        dep = Deployment(router, str(tmp_path), factory, config=cfg)
        while not dep.finished():
            dep.tick()
        assert dep.state == "done"
        # The export's manifest fingerprint IS the serving fingerprint.
        assert dep.weights_fp == manifest["weights_fingerprint"]
        np.testing.assert_array_equal(
            router.complete(p, 6, timeout=180), ref
        )
