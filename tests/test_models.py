"""Model zoo shape/grad tests (north-star families, BASELINE.json configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.models import get_model, MLModel
from ml_trainer_tpu.models.registry import available_models

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


def init_and_apply(model, x, train=False):
    rngs = {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}
    variables = model.init(rngs, x, train=False)
    kwargs = {"mutable": ["batch_stats"]} if "batch_stats" in variables else {}
    out = model.apply(variables, x, train=train,
                      rngs={"dropout": jax.random.PRNGKey(2)}, **kwargs)
    if isinstance(out, tuple):
        out = out[0]
    return variables, out


def test_registry_contains_all_families():
    names = available_models()
    for expected in ("mlmodel", "resnet18", "resnet50", "vit_b16",
                     "bert_base", "gpt2"):
        assert expected in names, names


def test_mlmodel_parity_shapes():
    """LeNet topology parity (ref: src/model.py:7-24): 32x32x3 -> 10 logits,
    62K params."""
    x = jnp.zeros((2, 32, 32, 3))
    variables, out = init_and_apply(MLModel(), x)
    assert out.shape == (2, 10)
    n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
    assert n_params == 62006  # exact torch LeNet param count


def test_resnet18_cifar_forward_and_batchstats():
    model = get_model("resnet18")
    x = jnp.zeros((2, 32, 32, 3))
    variables, out = init_and_apply(model, x, train=True)
    assert out.shape == (2, 10)
    assert "batch_stats" in variables


def test_resnet50_imagenet_shape():
    model = get_model("resnet50")
    x = jnp.zeros((1, 64, 64, 3))  # small spatial for test speed
    variables, out = init_and_apply(model, x)
    assert out.shape == (1, 1000)
    n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
    assert 23_000_000 < n_params < 27_000_000  # ~25.6M


def test_vit_tiny_forward_and_grad():
    model = get_model("vit_tiny")
    x = jnp.ones((2, 32, 32, 3))
    rngs = {"params": jax.random.PRNGKey(0)}
    variables = model.init(rngs, x, train=False)

    def loss(params):
        out = model.apply({"params": params}, x, train=False)
        return jnp.sum(out ** 2)

    grads = jax.grad(loss)(variables["params"])
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)


def test_vit_b16_bf16_activations():
    model = get_model("vit_b16", num_classes=10)
    x = jnp.zeros((1, 32, 32, 3), jnp.bfloat16)
    variables, out = init_and_apply(model, x)
    assert out.shape == (1, 10)
    assert out.dtype == jnp.float32  # head stays f32


def test_bert_tiny_classification_and_mask():
    model = get_model("bert_tiny", num_classes=2)
    ids = jnp.ones((2, 16), jnp.int32)
    rngs = {"params": jax.random.PRNGKey(0)}
    variables = model.init(rngs, ids, train=False)
    out_nomask = model.apply(variables, ids, train=False)
    assert out_nomask.shape == (2, 2)
    # Masking out padding changes the logits.
    mask = jnp.asarray([[1] * 8 + [0] * 8, [1] * 16])
    out_masked = model.apply(variables, ids, attention_mask=mask, train=False)
    assert not np.allclose(out_nomask, out_masked)


def test_gpt2_tiny_causal_lm_and_causality():
    model = get_model("gpt2_tiny")
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (1, 32)))
    rngs = {"params": jax.random.PRNGKey(0)}
    variables = model.init(rngs, ids, train=False)
    out = model.apply(variables, ids, train=False)
    assert out.shape == (1, 32, 1024)
    # Causality: perturbing a future token must not change earlier logits.
    ids2 = ids.at[0, 20].set((ids[0, 20] + 1) % 1024)
    out2 = model.apply(variables, ids2, train=False)
    np.testing.assert_allclose(out[0, :20], out2[0, :20], atol=1e-5)
    assert not np.allclose(out[0, 20:], out2[0, 20:])


def test_gpt2_param_count_is_124m():
    model = get_model("gpt2")
    ids = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, ids, train=False)
    n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
    assert 123_000_000 < n_params < 125_000_000  # 124M with tied head


def test_remat_gradients_match():
    """remat=True (jax.checkpoint per block) must not change values or
    gradients — only when activations are recomputed."""
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    kw = dict(vocab_size=128, embed_dim=32, depth=2, num_heads=2, max_len=32)
    m0 = get_model("gpt2_tiny", **kw)
    m1 = get_model("gpt2_tiny", remat=True, **kw)
    v = m0.init({"params": jax.random.PRNGKey(0)}, ids, train=False)

    def loss(params, model):
        return model.apply({"params": params}, ids, train=True).sum()

    l0, g0 = jax.value_and_grad(loss)(v["params"], m0)
    l1, g1 = jax.value_and_grad(loss)(v["params"], m1)
    assert np.allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        assert np.allclose(a, b, rtol=1e-5, atol=1e-6)


def test_remat_policy_dots_matches_none():
    """remat only changes WHAT is kept for backward, never the math: the
    'dots' policy gradient must equal full-recompute and no-remat."""
    import optax

    from ml_trainer_tpu.models import get_model

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32
    )
    targets = jnp.roll(ids, -1, axis=1)

    def grads(**kw):
        m = get_model("gpt2_tiny", vocab_size=256, max_len=32, **kw)
        v = m.init({"params": jax.random.PRNGKey(0)}, ids, train=False)

        def loss(p):
            out = m.apply({"params": p}, ids, train=True)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(out, targets)
            )

        return jax.grad(loss)(v["params"])

    g_plain = grads()
    g_full = grads(remat=True)
    g_dots = grads(remat=True, remat_policy="dots")
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_dots)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_dots)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

    with pytest.raises(ValueError, match="remat_policy"):
        grads(remat=True, remat_policy="everything")


def test_scaled_variant_param_counts_via_eval_shape():
    """The scaled registry variants carry their published parameter
    counts — checked via jax.eval_shape, which traces init without
    allocating or computing anything, so even the 774M config costs
    milliseconds here."""
    def count(name, seq_input=False, **kw):
        model = get_model(name, **kw)
        x = (
            jnp.zeros((1, 16), jnp.int32)
            if seq_input else jnp.zeros((1, 224, 224, 3), jnp.float32)
        )
        shapes = jax.eval_shape(
            lambda r: model.init({"params": r}, x, train=False),
            jax.random.PRNGKey(0),
        )
        return sum(
            int(np.prod(l.shape)) for l in jax.tree.leaves(shapes["params"])
        )

    # Published torchvision/HF counts (params only; BN stats excluded).
    assert count("resnet101") == 44_549_160
    assert count("resnet152") == 60_192_808
    # GPT-2 355M/774M: tied-head decoder (wte+wpe+blocks+ln_f).
    assert count("gpt2_medium", seq_input=True) == 354_823_168
    assert count("gpt2_large", seq_input=True) == 774_030_080
    # BERT-large encoder (+pooler +2-class head; ~335M — the published
    # "336M" additionally counts the MLM head this classifier omits).
    assert count("bert_large", seq_input=True) == 335_143_938
