"""Trainer integration tests — the 01-notebook flow as a test suite
(SURVEY.md §4's implication: the reference's notebooks are its de-facto
integration tests; here they are real pytest cases on a simulated mesh)."""

import os
import pickle

import jax
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel, Loader, load_history, load_model
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.utils.functions import custom_pre_process_function

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


def make_datasets(n_train=64, n_val=32, transform=False):
    t = custom_pre_process_function() if transform else None
    return (
        SyntheticCIFAR10(size=n_train, transform=t, seed=0),
        SyntheticCIFAR10(size=n_val, transform=t, seed=1),
    )


def make_trainer(tmp_path, epochs=2, batch_size=16, **config):
    config.setdefault("model_dir", str(tmp_path))
    return Trainer(
        MLModel(),
        datasets=make_datasets(),
        epochs=epochs,
        batch_size=batch_size,
        save_history=True,
        **config,
    )


def test_fit_produces_history_schema(tmp_path):
    trainer = make_trainer(tmp_path)
    trainer.fit()
    h = trainer.history
    # Exact schema: reference parity keys (ref: src/trainer.py:265-272)
    # plus the resilience layer's per-epoch skipped-step counts.
    assert set(h) == {
        "epochs", "train_loss", "val_loss", "train_metric", "val_metric",
        "metric_type", "skipped_steps",
    }
    assert h["epochs"] == [1, 2]
    assert len(h["train_loss"]) == 2 and len(h["val_metric"]) == 2
    assert h["metric_type"] == "accuracy"
    assert all(np.isfinite(v) for v in h["train_loss"])
    assert h["skipped_steps"] == [0, 0]  # healthy run: guard skipped nothing


def test_loss_decreases_on_learnable_data(tmp_path):
    """Train on a trivially separable synthetic problem; loss must drop."""
    rng = np.random.default_rng(0)
    targets = rng.integers(0, 10, size=(256,)).astype(np.int32)
    data = np.zeros((256, 32, 32, 3), dtype=np.float32)
    data[np.arange(256), 0, 0, 0] = targets  # label leaked into pixel
    from ml_trainer_tpu.data import ArrayDataset

    ds = ArrayDataset(data, targets)
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=5, batch_size=32,
        model_dir=str(tmp_path), lr=0.01,
    )
    trainer.fit()
    assert trainer.train_losses[-1] < trainer.train_losses[0]


def test_history_pickle_roundtrip_and_model_file(tmp_path):
    trainer = make_trainer(tmp_path)
    trainer.fit()
    h = load_history(str(tmp_path))
    assert h == trainer.history
    assert os.path.exists(os.path.join(str(tmp_path), "model.msgpack"))


def test_load_model_and_test_flow(tmp_path):
    """The 03-notebook flow: save → load_model → dataset-less Trainer →
    test() (ref: 03 nb cells 5-9; src/trainer.py:277-301)."""
    trainer = make_trainer(tmp_path)
    trainer.fit()
    loaded = load_model(MLModel(), str(tmp_path))
    # Dataset-less trainer exercises the warning path (ref: src/trainer.py:66-71).
    tester = Trainer(MLModel())
    test_loader = Loader(SyntheticCIFAR10(size=32, seed=2), batch_size=16, shuffle=True)
    out = tester.test(loaded, test_loader)
    assert isinstance(out, tuple) and len(out) == 2
    loss, acc = out
    assert np.isfinite(loss) and 0.0 <= acc <= 1.0


def test_metric_none_returns_loss_only(tmp_path):
    trainer = Trainer(
        MLModel(), datasets=make_datasets(), epochs=1, batch_size=16,
        model_dir=str(tmp_path), metric=None,
    )
    trainer.fit()
    test_loader = Loader(SyntheticCIFAR10(size=16, seed=3), batch_size=16)
    out = trainer.test(None, test_loader)
    assert isinstance(out, float)
    assert trainer.train_metrics == []


@pytest.mark.parametrize("scheduler", [
    "CosineAnnealingWarmRestarts", "StepLR", "ReduceLROnPlateau",
])
def test_schedulers_run_end_to_end(tmp_path, scheduler):
    trainer = make_trainer(tmp_path, epochs=2, scheduler=scheduler)
    trainer.fit()
    assert len(trainer.train_losses) == 2


def test_optimizer_and_criterion_variants(tmp_path):
    trainer = make_trainer(
        tmp_path, epochs=1, optimizer="adamw", criterion="cross_entropy",
        pred_function="logsoftmax",
    )
    trainer.fit()
    assert len(trainer.train_losses) == 1


def test_resume_from_checkpoint(tmp_path):
    """fit(resume=True) continues from the saved epoch — the capability the
    reference lacks (SURVEY.md §5 checkpoint/resume)."""
    t1 = make_trainer(tmp_path, epochs=2)
    t1.fit()
    step_after_2 = int(t1.state.step)
    t2 = Trainer(
        MLModel(), datasets=make_datasets(), epochs=4, batch_size=16,
        model_dir=str(tmp_path), save_history=True,
    )
    t2.fit(resume=True)
    assert int(t2.state.step) == step_after_2 * 2
    assert t2.history["epochs"] == [1, 2, 3, 4]
    assert t2.history["train_loss"][:2] == pytest.approx(t1.train_losses, abs=1e-6)


def test_perplexity_metric_finalized_at_epoch_level(tmp_path):
    """The engine applies the metric's epoch finalizer: with
    metric='perplexity' the recorded value is exp(mean NLL) — on the LM
    path where loss IS mean NLL, history metric == exp(history loss)."""
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.models import get_model

    ds = SyntheticTokens(size=16, seq_len=16, vocab_size=256, seed=0)
    t = Trainer(
        get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds), epochs=1,
        batch_size=8, model_dir=str(tmp_path), metric="perplexity",
        optimizer="adamw", lr=0.001, criterion="cross_entropy",
    )
    t.fit()
    # Not exactly equal (loss averages per-batch means; the metric path
    # recomputes from logits) but exp() must have been applied once:
    assert t.train_metrics[0] == pytest.approx(
        float(np.exp(t.train_losses[0])), rel=1e-3
    )


def test_seed_reproducibility(tmp_path):
    a = make_trainer(tmp_path / "a", epochs=1, seed=5)
    a.fit()
    b = make_trainer(tmp_path / "b", epochs=1, seed=5)
    b.fit()
    assert a.train_losses == pytest.approx(b.train_losses, rel=1e-5)


def test_unknown_config_key_raises():
    with pytest.raises(TypeError):
        Trainer(MLModel(), epochs=1, batch_size=8, nonsense=1)


def test_unknown_backend_raises():
    with pytest.raises(ValueError, match="backend"):
        Trainer(MLModel(), epochs=1, batch_size=8, backend="mpi")


def test_empty_loader_raises_clear_error(tmp_path):
    """A dataset shard smaller than the per-host batch must fail loudly at
    construction, not divide by zero after an epoch."""
    tiny = SyntheticCIFAR10(size=4)
    with pytest.raises(ValueError, match="no batches"):
        Trainer(
            MLModel(), datasets=(tiny, tiny), epochs=1, batch_size=64,
            model_dir=str(tmp_path), is_parallel=True,
        )


def test_plateau_state_survives_resume(tmp_path):
    """lr_scale and plateau bookkeeping are part of the checkpoint."""
    t1 = make_trainer(tmp_path, epochs=2, scheduler="ReduceLROnPlateau")
    t1._plateau.patience = 0  # force a reduction on the first bad epoch
    t1._plateau.best = -1.0   # every epoch is "bad"
    t1.fit()
    assert t1._lr_scale == pytest.approx(0.01)
    t2 = Trainer(
        MLModel(), datasets=make_datasets(), epochs=3, batch_size=16,
        model_dir=str(tmp_path), scheduler="ReduceLROnPlateau",
    )
    t2.fit(resume=True)
    assert t2._plateau.scale <= 0.01 + 1e-9


def test_steps_per_execution_matches_single_step(tmp_path):
    """K steps per dispatch (lax.scan over stacked batches) must reproduce
    the per-batch trajectory exactly: same history, same final params.
    64 train samples / batch 16 / K=3 -> one full chunk + a 1-batch tail,
    so both the scanned and the ragged-tail paths are exercised."""
    t1 = make_trainer(tmp_path / "a", epochs=2, seed=7)
    t1.fit()
    tk = make_trainer(
        tmp_path / "b", epochs=2, seed=7, steps_per_execution=3
    )
    tk.fit()
    assert np.allclose(t1.history["train_loss"], tk.history["train_loss"],
                       rtol=1e-5, atol=1e-6)
    assert np.allclose(t1.history["val_loss"], tk.history["val_loss"],
                       rtol=1e-5, atol=1e-6)
    flat1 = jax.tree_util.tree_leaves(t1.state.params)
    flatk = jax.tree_util.tree_leaves(tk.state.params)
    for a, b in zip(flat1, flatk):
        assert np.allclose(a, b, rtol=1e-5, atol=1e-6)


def test_steps_per_execution_on_mesh(tmp_path):
    """Multi-step dispatch composes with data-parallel sharding."""
    t = Trainer(
        MLModel(), datasets=make_datasets(128, 32), epochs=1, batch_size=32,
        is_parallel=True, steps_per_execution=2, model_dir=str(tmp_path),
        metric="accuracy",
    )
    t.fit()
    assert len(t.history["train_loss"]) == 1
    assert np.isfinite(t.history["train_loss"][0])


def test_steps_per_execution_ragged_batch_in_chunk_position(tmp_path):
    """An 80-sample dataset at batch 32 yields batches [32, 32, 16]: the
    ragged 16 would complete the K=3 chunk — it must divert to the tail
    path instead of crashing np.stack."""
    t = Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=80), SyntheticCIFAR10(size=32, seed=1)),
        epochs=1, batch_size=32, steps_per_execution=3,
        model_dir=str(tmp_path), metric="accuracy",
    )
    t.fit()
    assert len(t.history["train_loss"]) == 1
    assert np.isfinite(t.history["train_loss"][0])


def test_grad_clip_norm_limits_update(tmp_path):
    """With a tiny clip norm the SGD update must equal lr * clip * g/|g|;
    verified against a manual computation on the first step."""
    import jax.numpy as jnp

    clip = 1e-3
    trainer = make_trainer(
        tmp_path, epochs=1, optimizer="sgd", momentum=0.0, lr=1.0,
        grad_clip_norm=clip,
    )
    before = jax.tree.map(np.asarray, trainer.state.params)
    x, y = next(iter(trainer.train_loader))
    state, _, _ = trainer._train_step(
        trainer.state, jnp.asarray(x), jnp.asarray(y),
        jnp.asarray(1.0, jnp.float32),
    )
    after = jax.tree.map(np.asarray, state.params)
    deltas = jax.tree.leaves(
        jax.tree.map(lambda a, b: b - a, before, after)
    )
    global_norm = float(np.sqrt(sum((d ** 2).sum() for d in deltas)))
    # lr=1, no momentum: |update| == min(|g|, clip) == clip for a fresh net.
    assert global_norm <= clip * 1.01
    assert global_norm >= clip * 0.5  # gradient was actually clipped, not ~0


def test_grad_clip_invalid_raises(tmp_path):
    with pytest.raises(ValueError):
        make_trainer(tmp_path, grad_clip_norm=0.0)
    with pytest.raises(ValueError):
        make_trainer(tmp_path, ema_decay=1.0)


def test_ema_tracks_params_and_drives_eval(tmp_path):
    """EMA params follow ema = d*ema + (1-d)*p each step (manual recompute),
    and _state_variables()/save_model expose the EMA weights."""
    import jax.numpy as jnp

    d = 0.9
    trainer = make_trainer(
        tmp_path, epochs=1, batch_size=32, optimizer="sgd", momentum=0.0,
        lr=0.05, ema_decay=d,
    )
    ema = jax.tree.map(np.asarray, trainer.state.params)  # starts as copy
    state = trainer.state
    for i, (x, y) in enumerate(trainer.train_loader):
        state, _, _ = trainer._train_step(
            state, jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(1.0, jnp.float32),
        )
        new_params = jax.tree.map(np.asarray, state.params)
        ema = jax.tree.map(lambda e, p: d * e + (1 - d) * p, ema, new_params)
        if i == 2:
            break
    got = jax.tree.map(np.asarray, state.ema_params)
    for a, b in zip(jax.tree.leaves(ema), jax.tree.leaves(got)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    # EMA != raw params after updates, and eval variables serve the EMA.
    trainer.state = state
    raw = trainer._state_variables(ema=False)["params"]
    served = trainer._state_variables()["params"]
    assert any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(raw), jax.tree.leaves(served))
    )
    for a, b in zip(jax.tree.leaves(served), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6)


def test_ema_fit_and_resume_roundtrip(tmp_path):
    """fit() with EMA runs end-to-end; the EMA tree survives checkpoint
    resume (it lives inside TrainState)."""
    trainer = make_trainer(tmp_path, epochs=2, ema_decay=0.99)
    trainer.fit()
    ema_after = jax.tree.map(np.asarray, trainer.state.ema_params)
    resumed = make_trainer(tmp_path, epochs=2, ema_decay=0.99)
    resumed.fit(resume=True)  # epochs done -> restores state, trains nothing
    for a, b in zip(
        jax.tree.leaves(ema_after),
        jax.tree.leaves(jax.tree.map(np.asarray, resumed.state.ema_params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_ema_toggle_across_resume(tmp_path):
    """Checkpoints stay resumable when ema_decay changes between runs:
    off->on seeds the EMA from the restored params; on->off drops it."""
    make_trainer(tmp_path, epochs=1).fit()  # checkpoint without EMA
    on = make_trainer(tmp_path, epochs=2, ema_decay=0.99)
    on.fit(resume=True)  # must not crash; EMA seeded from restored params
    assert on.state.ema_params is not None
    off = make_trainer(tmp_path, epochs=3)
    off.fit(resume=True)  # EMA in checkpoint, disabled now -> dropped
    assert off.state.ema_params is None


def test_pre_ema_checkpoint_still_resumes(tmp_path):
    """A checkpoint written before TrainState grew ema_params (manifest has
    no such leaf) must restore into the new template."""
    import json as _json

    trainer = make_trainer(tmp_path, epochs=1)
    trainer.fit()
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    name = sorted(os.listdir(ckpt_dir))[-1]
    manifest_path = os.path.join(ckpt_dir, name, "manifest.json")
    with open(manifest_path) as f:
        manifest = _json.load(f)
    pruned = [
        leaf for leaf in manifest["leaves"]
        if leaf["path"][0] != "ema_params"
    ]
    assert len(pruned) < len(manifest["leaves"])  # the field was recorded
    manifest["leaves"] = pruned
    with open(manifest_path, "w") as f:
        _json.dump(manifest, f)
    resumed = make_trainer(tmp_path, epochs=2)
    resumed.fit(resume=True)  # old-format checkpoint restores cleanly
    assert resumed.history["epochs"] == [1, 2]


def test_grad_clip_toggle_across_resume(tmp_path):
    """opt_state structure is clip-flag-independent (always-chained), so a
    checkpoint saved without clipping resumes with it on, and vice versa."""
    make_trainer(tmp_path, epochs=1).fit()
    clipped = make_trainer(tmp_path, epochs=2, grad_clip_norm=1.0)
    clipped.fit(resume=True)
    assert clipped.history["epochs"] == [1, 2]
    off = make_trainer(tmp_path, epochs=3)
    off.fit(resume=True)
    assert off.history["epochs"] == [1, 2, 3]


def test_pre_chain_opt_state_checkpoint_restores(tmp_path):
    """Checkpoints written before the always-chain wrapper (opt_state one
    nesting level shallower) restore through the compat shim."""
    from ml_trainer_tpu.checkpoint import checkpoint as ckpt_mod
    from flax import serialization

    trainer = make_trainer(tmp_path, epochs=1)
    trainer.fit()
    path = ckpt_mod.latest_checkpoint(
        os.path.join(str(tmp_path), "checkpoints")
    )
    # Rewrite the checkpoint with the old (unchained) opt_state layout.
    state_dict = serialization.to_state_dict(
        ckpt_mod.fetch_to_host(trainer.state)
    )
    state_dict["opt_state"] = state_dict["opt_state"]["1"]
    ckpt_mod._write_checkpoint_dir(path, state_dict, trainer.history, 1)
    resumed = make_trainer(tmp_path, epochs=2)
    resumed.fit(resume=True)
    assert resumed.history["epochs"] == [1, 2]


def test_batchnorm_model_trains(tmp_path):
    """Regression: Trainer construction must tolerate batch_stats models in
    the aux-loss probe (the train-mode trace keeps batch_stats mutable) and
    running statistics must actually update over an epoch."""
    ds = SyntheticCIFAR10(size=16, seed=0)
    t = Trainer(
        get_model("resnet18"), datasets=(ds, ds), epochs=1, batch_size=8,
        model_dir=str(tmp_path), metric="accuracy",
    )
    assert t._has_batch_stats and not t._has_aux_losses
    # Copy to host before fit(): the donated train step consumes the
    # original device buffers.
    before = np.asarray(jax.tree.leaves(t.state.batch_stats)[0])
    t.fit()
    after = np.asarray(jax.tree.leaves(t.state.batch_stats)[0])
    assert np.isfinite(t.train_losses[0])
    assert not np.allclose(before, after)


def test_bf16_mixed_precision_training(tmp_path):
    """The ViT north-star recipe: bf16 activation compute, f32 params —
    params must STAY f32 through updates and the trajectory must be
    finite (BASELINE.json configs[3])."""
    import jax.numpy as jnp

    ds = SyntheticCIFAR10(size=32, seed=0)
    t = Trainer(
        get_model("vit_tiny", num_classes=10, dtype=jnp.bfloat16),
        datasets=(ds, ds), epochs=1, batch_size=8,
        model_dir=str(tmp_path), metric="accuracy", optimizer="adamw",
        lr=1e-3,
    )
    t.fit()
    assert np.isfinite(t.train_losses[0]) and np.isfinite(t.val_losses[0])
    dtypes = {leaf.dtype for leaf in jax.tree.leaves(t.state.params)}
    assert dtypes == {jnp.dtype(jnp.float32)}, dtypes


def test_early_stopping_halts_and_history_matches(tmp_path):
    """With lr=0 the val loss never improves after epoch 1, so patience=2
    stops at epoch 3; the history covers exactly the epochs that ran."""
    ds = SyntheticCIFAR10(size=64)
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=10, batch_size=16,
        model_dir=str(tmp_path), metric=None, optimizer="sgd", lr=0.0,
        early_stop_patience=2,
    )
    t.fit()
    assert len(t.train_losses) == 3
    assert t.history["epochs"] == [1, 2, 3]
    assert len(t.history["val_loss"]) == 3


def test_save_best_keeps_best_weights(tmp_path):
    """best/ must hold the weights of the BEST epoch, not the last: with
    lr=0 after construction only epoch 1 improves, so best/ freezes at
    the epoch-1 weights while the every-epoch export keeps overwriting."""
    import os

    import jax

    ds = SyntheticCIFAR10(size=64)
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path), metric=None, optimizer="sgd", lr=0.05,
        save_best=True,
    )
    t.fit()
    from ml_trainer_tpu import load_model

    best_after_1 = load_model(MLModel(), os.path.join(str(tmp_path), "best"))
    # Keep training (fresh Trainer, resumed state, lr=0 -> no improvement:
    # the val loss stays exactly flat, so best/ must not move).
    t2 = Trainer(
        MLModel(), datasets=(ds, ds), epochs=3, batch_size=16,
        model_dir=str(tmp_path), metric=None, optimizer="sgd", lr=0.0,
        save_best=True,
    )
    t2.fit(resume=True)
    best_after_3 = load_model(
        MLModel(), os.path.join(str(tmp_path), "best")
    )
    for a, b in zip(
        jax.tree.leaves(best_after_1.variables),
        jax.tree.leaves(best_after_3.variables),
    ):
        np.testing.assert_array_equal(a, b)


def test_early_stop_state_survives_resume(tmp_path):
    """best/bad counters live in checkpoints: a resumed run continues the
    patience countdown instead of resetting it."""
    ds = SyntheticCIFAR10(size=64)
    kw = dict(
        datasets=(ds, ds), batch_size=16, model_dir=str(tmp_path),
        metric=None, optimizer="sgd", lr=0.0, early_stop_patience=3,
    )
    t1 = Trainer(MLModel(), epochs=2, **kw)
    t1.fit()  # 2 epochs: epoch 2 is the first bad epoch (lr=0)
    assert t1._bad_epochs == 1
    t2 = Trainer(MLModel(), epochs=10, **kw)
    t2.fit(resume=True)
    # Resumed with bad=1: stops after 2 more bad epochs (epoch 4).
    assert len(t2.train_losses) == 4


def test_resumed_run_already_out_of_patience_trains_zero_epochs(tmp_path):
    ds = SyntheticCIFAR10(size=64)
    kw = dict(
        datasets=(ds, ds), batch_size=16, model_dir=str(tmp_path),
        metric=None, optimizer="sgd", lr=0.0, early_stop_patience=1,
    )
    t1 = Trainer(MLModel(), epochs=3, **kw)
    t1.fit()  # stops at epoch 2 (patience 1, lr=0)
    assert len(t1.train_losses) == 2
    t2 = Trainer(MLModel(), epochs=10, **kw)
    t2.fit(resume=True)
    # Out of patience at resume time: not a single extra epoch trains.
    assert len(t2.train_losses) == 2


def test_predict_returns_ordered_outputs(tmp_path):
    """predict() yields one output row per sample in loader order, maps
    them through the configured pred_function, and matches a direct
    forward pass."""
    import jax

    from ml_trainer_tpu.data import Loader

    ds = SyntheticCIFAR10(size=48)
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path), metric="accuracy",
        pred_function="softmax",
    )
    t.fit()
    loader = Loader(SyntheticCIFAR10(size=24, seed=3), batch_size=10)
    preds = t.predict(loader)
    assert preds.shape == (24, 10)  # ragged final batch of 4 included
    np.testing.assert_allclose(preds.sum(axis=-1), 1.0, rtol=1e-5)
    # Matches a hand-rolled forward over the same batches.
    xs = np.concatenate([np.asarray(b[0]) for b in loader])
    params = {"params": jax.device_get(t.state.params)}
    direct = jax.nn.softmax(
        t.model.apply(params, jax.numpy.asarray(xs), train=False), axis=-1
    )
    np.testing.assert_allclose(preds, np.asarray(direct), atol=1e-5)
    raw = t.predict(loader, apply_pred_function=False)
    assert not np.allclose(raw.sum(axis=-1), 1.0)
