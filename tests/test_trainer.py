"""Trainer integration tests — the 01-notebook flow as a test suite
(SURVEY.md §4's implication: the reference's notebooks are its de-facto
integration tests; here they are real pytest cases on a simulated mesh)."""

import os
import pickle

import jax
import numpy as np
import pytest

from ml_trainer_tpu import Trainer, MLModel, Loader, load_history, load_model
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def make_datasets(n_train=64, n_val=32, transform=False):
    t = custom_pre_process_function() if transform else None
    return (
        SyntheticCIFAR10(size=n_train, transform=t, seed=0),
        SyntheticCIFAR10(size=n_val, transform=t, seed=1),
    )


def make_trainer(tmp_path, epochs=2, batch_size=16, **config):
    config.setdefault("model_dir", str(tmp_path))
    return Trainer(
        MLModel(),
        datasets=make_datasets(),
        epochs=epochs,
        batch_size=batch_size,
        save_history=True,
        **config,
    )


def test_fit_produces_history_schema(tmp_path):
    trainer = make_trainer(tmp_path)
    trainer.fit()
    h = trainer.history
    # Exact schema parity (ref: src/trainer.py:265-272).
    assert set(h) == {
        "epochs", "train_loss", "val_loss", "train_metric", "val_metric",
        "metric_type",
    }
    assert h["epochs"] == [1, 2]
    assert len(h["train_loss"]) == 2 and len(h["val_metric"]) == 2
    assert h["metric_type"] == "accuracy"
    assert all(np.isfinite(v) for v in h["train_loss"])


def test_loss_decreases_on_learnable_data(tmp_path):
    """Train on a trivially separable synthetic problem; loss must drop."""
    rng = np.random.default_rng(0)
    targets = rng.integers(0, 10, size=(256,)).astype(np.int32)
    data = np.zeros((256, 32, 32, 3), dtype=np.float32)
    data[np.arange(256), 0, 0, 0] = targets  # label leaked into pixel
    from ml_trainer_tpu.data import ArrayDataset

    ds = ArrayDataset(data, targets)
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=5, batch_size=32,
        model_dir=str(tmp_path), lr=0.01,
    )
    trainer.fit()
    assert trainer.train_losses[-1] < trainer.train_losses[0]


def test_history_pickle_roundtrip_and_model_file(tmp_path):
    trainer = make_trainer(tmp_path)
    trainer.fit()
    h = load_history(str(tmp_path))
    assert h == trainer.history
    assert os.path.exists(os.path.join(str(tmp_path), "model.msgpack"))


def test_load_model_and_test_flow(tmp_path):
    """The 03-notebook flow: save → load_model → dataset-less Trainer →
    test() (ref: 03 nb cells 5-9; src/trainer.py:277-301)."""
    trainer = make_trainer(tmp_path)
    trainer.fit()
    loaded = load_model(MLModel(), str(tmp_path))
    # Dataset-less trainer exercises the warning path (ref: src/trainer.py:66-71).
    tester = Trainer(MLModel())
    test_loader = Loader(SyntheticCIFAR10(size=32, seed=2), batch_size=16, shuffle=True)
    out = tester.test(loaded, test_loader)
    assert isinstance(out, tuple) and len(out) == 2
    loss, acc = out
    assert np.isfinite(loss) and 0.0 <= acc <= 1.0


def test_metric_none_returns_loss_only(tmp_path):
    trainer = Trainer(
        MLModel(), datasets=make_datasets(), epochs=1, batch_size=16,
        model_dir=str(tmp_path), metric=None,
    )
    trainer.fit()
    test_loader = Loader(SyntheticCIFAR10(size=16, seed=3), batch_size=16)
    out = trainer.test(None, test_loader)
    assert isinstance(out, float)
    assert trainer.train_metrics == []


@pytest.mark.parametrize("scheduler", [
    "CosineAnnealingWarmRestarts", "StepLR", "ReduceLROnPlateau",
])
def test_schedulers_run_end_to_end(tmp_path, scheduler):
    trainer = make_trainer(tmp_path, epochs=2, scheduler=scheduler)
    trainer.fit()
    assert len(trainer.train_losses) == 2


def test_optimizer_and_criterion_variants(tmp_path):
    trainer = make_trainer(
        tmp_path, epochs=1, optimizer="adamw", criterion="cross_entropy",
        pred_function="logsoftmax",
    )
    trainer.fit()
    assert len(trainer.train_losses) == 1


def test_resume_from_checkpoint(tmp_path):
    """fit(resume=True) continues from the saved epoch — the capability the
    reference lacks (SURVEY.md §5 checkpoint/resume)."""
    t1 = make_trainer(tmp_path, epochs=2)
    t1.fit()
    step_after_2 = int(t1.state.step)
    t2 = Trainer(
        MLModel(), datasets=make_datasets(), epochs=4, batch_size=16,
        model_dir=str(tmp_path), save_history=True,
    )
    t2.fit(resume=True)
    assert int(t2.state.step) == step_after_2 * 2
    assert t2.history["epochs"] == [1, 2, 3, 4]
    assert t2.history["train_loss"][:2] == pytest.approx(t1.train_losses, abs=1e-6)


def test_seed_reproducibility(tmp_path):
    a = make_trainer(tmp_path / "a", epochs=1, seed=5)
    a.fit()
    b = make_trainer(tmp_path / "b", epochs=1, seed=5)
    b.fit()
    assert a.train_losses == pytest.approx(b.train_losses, rel=1e-5)


def test_unknown_config_key_raises():
    with pytest.raises(TypeError):
        Trainer(MLModel(), epochs=1, batch_size=8, nonsense=1)


def test_unknown_backend_raises():
    with pytest.raises(ValueError, match="backend"):
        Trainer(MLModel(), epochs=1, batch_size=8, backend="mpi")


def test_empty_loader_raises_clear_error(tmp_path):
    """A dataset shard smaller than the per-host batch must fail loudly at
    construction, not divide by zero after an epoch."""
    tiny = SyntheticCIFAR10(size=4)
    with pytest.raises(ValueError, match="no batches"):
        Trainer(
            MLModel(), datasets=(tiny, tiny), epochs=1, batch_size=64,
            model_dir=str(tmp_path), is_parallel=True,
        )


def test_plateau_state_survives_resume(tmp_path):
    """lr_scale and plateau bookkeeping are part of the checkpoint."""
    t1 = make_trainer(tmp_path, epochs=2, scheduler="ReduceLROnPlateau")
    t1._plateau.patience = 0  # force a reduction on the first bad epoch
    t1._plateau.best = -1.0   # every epoch is "bad"
    t1.fit()
    assert t1._lr_scale == pytest.approx(0.01)
    t2 = Trainer(
        MLModel(), datasets=make_datasets(), epochs=3, batch_size=16,
        model_dir=str(tmp_path), scheduler="ReduceLROnPlateau",
    )
    t2.fit(resume=True)
    assert t2._plateau.scale <= 0.01 + 1e-9


def test_steps_per_execution_matches_single_step(tmp_path):
    """K steps per dispatch (lax.scan over stacked batches) must reproduce
    the per-batch trajectory exactly: same history, same final params.
    64 train samples / batch 16 / K=3 -> one full chunk + a 1-batch tail,
    so both the scanned and the ragged-tail paths are exercised."""
    t1 = make_trainer(tmp_path / "a", epochs=2, seed=7)
    t1.fit()
    tk = make_trainer(
        tmp_path / "b", epochs=2, seed=7, steps_per_execution=3
    )
    tk.fit()
    assert np.allclose(t1.history["train_loss"], tk.history["train_loss"],
                       rtol=1e-5, atol=1e-6)
    assert np.allclose(t1.history["val_loss"], tk.history["val_loss"],
                       rtol=1e-5, atol=1e-6)
    flat1 = jax.tree_util.tree_leaves(t1.state.params)
    flatk = jax.tree_util.tree_leaves(tk.state.params)
    for a, b in zip(flat1, flatk):
        assert np.allclose(a, b, rtol=1e-5, atol=1e-6)


def test_steps_per_execution_on_mesh(tmp_path):
    """Multi-step dispatch composes with data-parallel sharding."""
    t = Trainer(
        MLModel(), datasets=make_datasets(128, 32), epochs=1, batch_size=32,
        is_parallel=True, steps_per_execution=2, model_dir=str(tmp_path),
        metric="accuracy",
    )
    t.fit()
    assert len(t.history["train_loss"]) == 1
    assert np.isfinite(t.history["train_loss"][0])


def test_steps_per_execution_ragged_batch_in_chunk_position(tmp_path):
    """An 80-sample dataset at batch 32 yields batches [32, 32, 16]: the
    ragged 16 would complete the K=3 chunk — it must divert to the tail
    path instead of crashing np.stack."""
    t = Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=80), SyntheticCIFAR10(size=32, seed=1)),
        epochs=1, batch_size=32, steps_per_execution=3,
        model_dir=str(tmp_path), metric="accuracy",
    )
    t.fit()
    assert len(t.history["train_loss"]) == 1
    assert np.isfinite(t.history["train_loss"][0])
