"""Distributed-path tests on the simulated 8-device CPU mesh — the
TPU-native analog of the reference's gloo/local_gpu staging (SURVEY.md §4):
gradient-psum equivalence to single-device runs, tensor-parallel training,
ring attention vs full attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ml_trainer_tpu import Trainer, MLModel, Loader
from ml_trainer_tpu.data import ArrayDataset, SyntheticCIFAR10, SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.parallel import (
    batch_sharding,
    create_mesh,
    ring_attention,
    rules_for,
)
from ml_trainer_tpu.ops.attention import dot_product_attention

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


def test_data_parallel_matches_single_device(tmp_path):
    """The gradient-psum path (8-way sharded batch, replicated params) must
    produce the same training trajectory as one device — the correctness
    contract DDP gives the reference (ref: src/trainer.py:98, 152-158)."""
    ds = SyntheticCIFAR10(size=64, seed=0)
    common = dict(epochs=2, batch_size=32, seed=7, lr=0.01)
    t_single = Trainer(
        MLModel(), datasets=(ds, ds), model_dir=str(tmp_path / "s"), **common
    )
    t_single.fit()
    t_mesh = Trainer(
        MLModel(), datasets=(ds, ds), model_dir=str(tmp_path / "m"),
        is_parallel=True, backend="cpu", **common,
    )
    assert t_mesh._data_parallel == 8
    t_mesh.fit()
    np.testing.assert_allclose(
        t_single.train_losses, t_mesh.train_losses, rtol=1e-4
    )
    # Final params agree too (tolerance allows for psum reduction-order
    # float noise accumulated over the run).
    for a, b in zip(
        jax.tree.leaves(t_single.state.params),
        jax.tree.leaves(t_mesh.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_tensor_parallel_training_runs_and_matches(tmp_path):
    """dp=4 × tp=2 GPT-2-tiny training step: runs, loss finite, and the
    first-epoch loss matches a pure-DP run (sharding must not change math)."""
    ds = SyntheticTokens(size=64, seq_len=32, vocab_size=1024, seed=0)
    common = dict(
        epochs=1, batch_size=16, seed=3, lr=0.01,
        optimizer="adamw", metric=None,
    )
    t_dp = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "dp"), is_parallel=True, backend="cpu",
        **common,
    )
    t_dp.fit()
    t_tp = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "tp"), is_parallel=True, backend="cpu",
        mesh_shape={"data": 4, "tensor": 2},
        sharding_rules=rules_for("gpt2", "tp"),
        **common,
    )
    assert t_tp._data_parallel == 4
    # qkv kernels actually sharded over the tensor axis:
    qkv = t_tp.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "tensor")
    # ... and the optimizer moments INHERIT that sharding rather than
    # being replicated (regression: jitted tx.init erased the param
    # shardings and the placement pass then replicated every moment).
    moment_specs = {
        leaf.sharding.spec
        for leaf in jax.tree.leaves(t_tp.state.opt_state)
        if hasattr(leaf, "ndim") and leaf.ndim >= 2
    }
    assert P(None, "tensor") in moment_specs, moment_specs
    t_tp.fit()
    np.testing.assert_allclose(t_dp.train_losses, t_tp.train_losses, rtol=1e-3)


def test_fsdp_training_runs(tmp_path):
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)
    t = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        mesh_shape={"fsdp": 8}, sharding_rules=rules_for("gpt2", "fsdp"),
        epochs=1, batch_size=16, metric=None,
    )
    emb = t.state.params["tok_embed"]["embedding"]
    # FSDP_RULES shards embedding tables on the FEATURE dim (vocab sizes
    # like GPT-2's 50257 rarely divide the axis; the feature dim always
    # does) — see parallel/tp_rules.py FSDP_RULES.
    assert emb.sharding.spec == P(None, "fsdp")
    t.fit()
    assert np.isfinite(t.train_losses[0])


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring attention over an 8-way sequence shard == full attention."""
    mesh = create_mesh({"sequence": 8})
    rng = np.random.default_rng(0)
    shape = (2, 4, 64, 16)  # S=64 -> 8 per device
    q, k, v = (
        jnp.asarray(rng.normal(size=shape), dtype=jnp.float32) for _ in range(3)
    )
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_under_jit_with_sharded_inputs():
    mesh = create_mesh({"sequence": 8})
    rng = np.random.default_rng(1)
    shape = (1, 2, 128, 16)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape), dtype=jnp.float32) for _ in range(3)
    )
    seq_sharding = jax.sharding.NamedSharding(mesh, P(None, None, "sequence", None))
    qs, ks, vs = (jax.device_put(t, seq_sharding) for t in (q, k, v))
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
    )(qs, ks, vs)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_dryrun_multichip_various_device_counts(n):
    import __graft_entry__ as graft

    graft.dryrun_multichip(n)


def test_gpt2_pos_embed_rule_applies(tmp_path):
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)
    t = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        mesh_shape={"data": 4, "tensor": 2},
        sharding_rules=rules_for("gpt2", "tp"),
        epochs=1, batch_size=16, metric=None,
    )
    assert t.state.params["pos_embed"].sharding.spec == P(None, None, "tensor")
    # optimizer scalar leaves are mesh-replicated, not host-local
    import jax as _jax
    for leaf in _jax.tree.leaves(t.state.opt_state):
        assert isinstance(leaf.sharding, _jax.sharding.NamedSharding)


def test_mesh_shape_without_is_parallel(tmp_path):
    """Single-process multi-chip: explicit mesh_shape is honored without the
    distributed rendezvous."""
    ds = SyntheticCIFAR10(size=64, seed=0)
    t = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=16,
        model_dir=str(tmp_path), mesh_shape={"data": 8},
    )
    assert t._data_parallel == 8
    t.fit()
    assert np.isfinite(t.train_losses[0])


def test_ring_sequence_parallel_training_matches_dp(tmp_path):
    """VERDICT r1 #6: sequence parallelism integrated end-to-end — a
    gpt2_tiny whose blocks run ring attention over a {data:2, sequence:4}
    mesh trains through the full Trainer path and matches the pure-DP
    trajectory (the ring must not change the math)."""
    ds = SyntheticTokens(size=32, seq_len=64, vocab_size=1024, seed=0)
    common = dict(
        epochs=2, batch_size=8, seed=3, lr=0.01, optimizer="adamw",
        metric=None,
    )
    t_dp = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "dp"), is_parallel=True, backend="cpu",
        **common,
    )
    t_dp.fit()

    mesh = create_mesh({"data": 2, "sequence": 4})
    t_sp = Trainer(
        get_model("gpt2_tiny", attention_impl="ring", mesh=mesh),
        datasets=(ds, ds),
        model_dir=str(tmp_path / "sp"), is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "sequence": 4},
        **common,
    )
    # Token batches really shard the sequence dim over the sequence axis.
    assert t_sp._batch_sharding.spec == P(("data",), "sequence")
    t_sp.fit()
    np.testing.assert_allclose(
        t_dp.train_losses, t_sp.train_losses, rtol=1e-3
    )
    np.testing.assert_allclose(t_dp.val_losses, t_sp.val_losses, rtol=1e-3)


def test_zero1_opt_state_sharding_matches_replicated(tmp_path):
    """shard_opt_state=True (ZeRO-1 placement: momenta partitioned over the
    data axis) must train the same trajectory as replicated opt state —
    it is a memory/placement decision, not a math change."""
    ds = SyntheticCIFAR10(size=128, seed=0)
    common = dict(
        epochs=2, batch_size=32, seed=7, lr=0.01, optimizer="adam",
        is_parallel=True, backend="cpu",
    )
    t_rep = Trainer(
        MLModel(), datasets=(ds, ds), model_dir=str(tmp_path / "r"), **common
    )
    t_rep.fit()
    t_z1 = Trainer(
        MLModel(), datasets=(ds, ds), model_dir=str(tmp_path / "z"),
        shard_opt_state=True, **common,
    )
    # At least one adam moment leaf actually lands sharded over data.
    specs = [
        getattr(l, "sharding", None)
        for l in jax.tree.leaves(t_z1.state.opt_state)
        if hasattr(l, "ndim") and l.ndim > 0
    ]
    assert any(
        s is not None and any(ax is not None for ax in s.spec) for s in specs
    ), "no optimizer-state leaf was partitioned"
    t_z1.fit()
    np.testing.assert_allclose(t_rep.train_losses, t_z1.train_losses, rtol=1e-4)
    for a, b in zip(
        jax.tree.leaves(t_rep.state.params), jax.tree.leaves(t_z1.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_sharded_dp_update_matches_fused(tmp_path):
    """dp_update='sharded' (bucketed reduce-scatter backward + 1/N-shard
    weight update + bucketed all-gather, arXiv 2004.13336) must train the
    same trajectory as the fused-psum step at fp32 — the rewrite
    restructures the communication, not the math.  Losses pin tightly;
    params allow the float noise of a different reduction order."""
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_bucket_bytes,
        reset_comm_stats,
    )

    ds = SyntheticTokens(size=64, seq_len=32, vocab_size=256, seed=0)
    common = dict(
        epochs=2, batch_size=16, seed=3, lr=0.01, optimizer="adamw",
        metric=None, is_parallel=True, backend="cpu",
    )
    t_fused = Trainer(
        get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds),
        model_dir=str(tmp_path / "f"), **common,
    )
    t_fused.fit()
    reset_comm_stats()
    t_sh = Trainer(
        get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds),
        model_dir=str(tmp_path / "s"), dp_update="sharded", bucket_mb=0.25,
        **common,
    )
    # The plan really bucketed (several reduce-scatters, not one tail
    # collective) and ZeRO-1 moment placement was implied.
    assert len(t_sh._bucket_plan.buckets) > 1
    assert t_sh._bucket_plan.overlap_fraction > 0
    moment_specs = {
        leaf.sharding.spec
        for leaf in jax.tree.leaves(t_sh.state.opt_state)
        if hasattr(leaf, "ndim") and leaf.ndim > 0
    }
    assert P("data") in moment_specs, moment_specs
    t_sh.fit()
    # Zero recompiles across the run: ONE compiled program.
    assert t_sh._train_step._cache_size() == 1
    np.testing.assert_allclose(
        t_fused.train_losses, t_sh.train_losses, rtol=1e-4
    )
    np.testing.assert_allclose(t_fused.val_losses, t_sh.val_losses, rtol=1e-4)
    for a, b in zip(
        jax.tree.leaves(t_fused.state.params),
        jax.tree.leaves(t_sh.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)
    # Params come home replicated (the all-gather happened INSIDE the
    # step — exports/checkpoints see the same placement as fused).
    for leaf in jax.tree.leaves(t_sh.state.params):
        assert leaf.sharding.spec == P(), leaf.sharding.spec
    # Per-bucket comm accounting flowed: one reduce-scatter and one
    # all-gather entry per bucket.
    by_bucket = comm_bucket_bytes()
    assert len(by_bucket.get("reduce_scatter", {})) == len(
        t_sh._bucket_plan.buckets
    )
    assert len(by_bucket.get("all_gather", {})) == len(
        t_sh._bucket_plan.buckets
    )


def test_sharded_dp_update_bf16_scaling_composes(tmp_path):
    """The full tentpole composition: bucketed sharded update x bf16
    compute x dynamic loss scaling trains finite with a single compiled
    program, and the scale survives at its healthy value."""
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    t = Trainer(
        get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        dp_update="sharded", precision="bf16", epochs=2, batch_size=16,
        optimizer="adamw", metric=None, lr=0.01,
    )
    assert jnp.dtype(t.model.dtype) == jnp.dtype(jnp.bfloat16)
    t.fit()
    assert t._train_step._cache_size() == 1
    assert all(np.isfinite(t.train_losses))
    assert float(t.state.loss_scale) > 0
    assert t.skipped_steps == [0, 0]


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    """Ulysses (a2a head-scatter) over an 8-way sequence shard == full
    attention; the 8-way axis divides H=8."""
    from ml_trainer_tpu.parallel import ulysses_attention

    mesh = create_mesh({"sequence": 8})
    rng = np.random.default_rng(0)
    shape = (2, 8, 64, 16)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape), dtype=jnp.float32) for _ in range(3)
    )
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ulysses_under_jit_with_sharded_inputs_and_grad():
    from ml_trainer_tpu.parallel import ulysses_attention

    mesh = create_mesh({"sequence": 8})
    rng = np.random.default_rng(1)
    shape = (1, 8, 128, 16)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape), dtype=jnp.float32) for _ in range(3)
    )
    seq_sharding = jax.sharding.NamedSharding(mesh, P(None, None, "sequence", None))
    qs, ks, vs = (jax.device_put(t, seq_sharding) for t in (q, k, v))

    def loss_u(a, b, c):
        return ulysses_attention(a, b, c, mesh, causal=True).sum()

    def loss_ref(a, b, c):
        return dot_product_attention(a, b, c, causal=True).sum()

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(qs, ks, vs)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_head_divisibility_error():
    from ml_trainer_tpu.parallel import ulysses_attention

    mesh = create_mesh({"sequence": 8})
    q = jnp.zeros((1, 6, 64, 16))
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, q, q, mesh)


def test_ulysses_sequence_parallel_training_matches_dp(tmp_path):
    """VERDICT r2 #7: Ulysses integrated end-to-end, parity with the ring
    integration — gpt2_tiny with ``attention_impl='ulysses'`` (heads
    scattered / sequence gathered by all-to-all inside each block) trains
    through the full Trainer on a {data:2, sequence:4} mesh and matches
    the pure-DP trajectory."""
    ds = SyntheticTokens(size=32, seq_len=64, vocab_size=1024, seed=0)
    common = dict(
        epochs=2, batch_size=8, seed=3, lr=0.01, optimizer="adamw",
        metric=None,
    )
    t_dp = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path / "dp"), is_parallel=True, backend="cpu",
        **common,
    )
    t_dp.fit()

    mesh = create_mesh({"data": 2, "sequence": 4})
    t_sp = Trainer(
        get_model("gpt2_tiny", attention_impl="ulysses", mesh=mesh),
        datasets=(ds, ds),
        model_dir=str(tmp_path / "sp"), is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "sequence": 4},
        **common,
    )
    assert t_sp._batch_sharding.spec == P(("data",), "sequence")
    t_sp.fit()
    np.testing.assert_allclose(
        t_dp.train_losses, t_sp.train_losses, rtol=1e-3
    )
    np.testing.assert_allclose(t_dp.val_losses, t_sp.val_losses, rtol=1e-3)


def test_test_keeps_sharded_state_sharded(tmp_path):
    """VERDICT r2 weak #6: ``test()`` on a TP-trained state must NOT force
    the params replicated — that all-gather defeats the sharding and OOMs
    exactly on the models sharding exists for.  Trained-state leaves keep
    their NamedSharding; host-loaded numpy leaves still place replicated."""
    ds = SyntheticTokens(size=16, seq_len=32, vocab_size=1024, seed=0)
    t = Trainer(
        get_model("gpt2_tiny"), datasets=(ds, ds),
        model_dir=str(tmp_path), is_parallel=True, backend="cpu",
        mesh_shape={"data": 4, "tensor": 2},
        sharding_rules=rules_for("gpt2", "tp"),
        epochs=1, batch_size=8, metric=None,
    )
    placed = t._place_eval_variables(t._state_variables())
    qkv = placed["params"]["block0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "tensor"), qkv.sharding.spec
    # Host numpy leaves (a loaded checkpoint) still get replicated.
    host = jax.tree.map(np.asarray, t._state_variables())
    placed_host = t._place_eval_variables(host)
    qkv_h = placed_host["params"]["block0"]["attn"]["qkv"]["kernel"]
    assert qkv_h.sharding.spec == P(), qkv_h.sharding.spec
    # And the full test() path runs on the sharded state.
    loader = Loader(ds, batch_size=8)
    loss = t.test(None, loader)
    assert np.isfinite(loss)


def test_graft_entry_contract():
    """entry() must return a jittable forward and example args whose
    traced output is the flagship LM's [B, S, vocab] logits."""
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (1, 128, 50257), out.shape
    assert out.dtype == jnp.float32


def test_long_context_stack_composes(tmp_path):
    """The long-context levers compose in ONE training run: ring sequence
    parallelism x per-block remat with the 'dots' policy x chunked LM
    loss (self-loss model).  Trajectory must match the plain-DP dense
    model — none of the three changes the math."""
    ds = SyntheticTokens(size=16, seq_len=64, vocab_size=512, seed=4)
    common = dict(
        epochs=2, batch_size=8, seed=5, lr=0.01, optimizer="adamw",
        metric=None,
    )
    t_ref = Trainer(
        get_model("gpt2_tiny", vocab_size=512),
        datasets=(ds, ds), model_dir=str(tmp_path / "ref"),
        is_parallel=True, backend="cpu", **common,
    )
    t_ref.fit()

    mesh = create_mesh({"data": 2, "sequence": 4})
    t_stack = Trainer(
        get_model(
            "gpt2_tiny", vocab_size=512, attention_impl="ring", mesh=mesh,
            remat=True, remat_policy="dots", loss_chunk=16,
        ),
        datasets=(ds, ds), model_dir=str(tmp_path / "stack"),
        is_parallel=True, backend="cpu",
        mesh_shape={"data": 2, "sequence": 4}, **common,
    )
    # Guard against a vacuous pass: the token batch must really shard the
    # sequence axis (same assertion as the sibling ring test).
    assert t_stack._batch_sharding.spec == P(("data",), "sequence")
    t_stack.fit()
    np.testing.assert_allclose(
        t_ref.train_losses, t_stack.train_losses, rtol=1e-3
    )
    np.testing.assert_allclose(
        t_ref.val_losses, t_stack.val_losses, rtol=1e-3
    )


def test_validate_tp_mesh_rejects_head_splitting_degree():
    """GQA guard (ADVICE r4): a tensor degree that does not divide
    num_kv_heads must raise, not silently shard mid-head."""
    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.tp_rules import validate_tp_mesh

    llama = get_model("llama_tiny")  # 4 q heads / 2 kv heads
    validate_tp_mesh(llama, create_mesh({"data": 4, "tensor": 2}))  # ok
    with pytest.raises(ValueError, match="num_kv_heads"):
        validate_tp_mesh(llama, create_mesh({"data": 2, "tensor": 4}))
    # Degree must divide the q-head count too (8 > 4 heads).
    with pytest.raises(ValueError, match="num_heads"):
        validate_tp_mesh(
            get_model("gpt2_tiny"), create_mesh({"tensor": 8})
        )
    # Meshes without a tensor axis are always fine.
    validate_tp_mesh(llama, create_mesh({"data": 8}))
