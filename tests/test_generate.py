"""KV-cache autoregressive generation (ml_trainer_tpu.generate).

The decode loop is one jitted lax.scan over a fixed-size cache; the
ground truth is the naive approach — a full causal forward over the
growing sequence each step — which the cached path must reproduce
token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model

# Integration layer: multi-epoch fits / trajectory equality / compiled
# programs — the CI fast lane is `-m 'not slow'` (see pyproject.toml).
pytestmark = pytest.mark.slow


def _naive_greedy(model, variables, ids, n):
    seq = ids
    for _ in range(n):
        logits = model.apply(variables, seq, train=False)
        nxt = jnp.argmax(logits[:, -1], -1).astype(seq.dtype)[:, None]
        seq = jnp.concatenate([seq, nxt], axis=1)
    return seq


def _model_and_ids(seed=0, b=2, p=5):
    model = get_model("gpt2_tiny")
    ids = jnp.asarray(
        np.random.default_rng(seed).integers(0, 1024, (b, p)), jnp.int32
    )
    variables = model.init({"params": jax.random.PRNGKey(seed)}, ids,
                           train=False)
    return model, variables, ids


def test_greedy_generate_matches_naive_full_forward():
    model, variables, ids = _model_and_ids()
    out = generate(model, variables, ids, max_new_tokens=8)
    ref = _naive_greedy(model, variables, ids, 8)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_prefix_is_the_prompt_and_sampling_runs():
    model, variables, ids = _model_and_ids(seed=1)
    out = generate(model, variables, ids, max_new_tokens=6,
                   temperature=0.8, rng=jax.random.PRNGKey(7))
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(ids))
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 1024).all()
    # Different seeds sample different continuations (overwhelmingly).
    out2 = generate(model, variables, ids, max_new_tokens=6,
                    temperature=0.8, rng=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(out), np.asarray(out2))


def test_generate_rejects_overflow():
    model, variables, ids = _model_and_ids()
    with pytest.raises(ValueError, match="max_len"):
        generate(model, variables, ids, max_new_tokens=10_000)


def test_single_token_prompt():
    model, variables, ids = _model_and_ids(b=1, p=1, seed=2)
    out = generate(model, variables, ids, max_new_tokens=4)
    ref = _naive_greedy(model, variables, ids, 4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_zero_new_tokens_returns_prompt():
    model, variables, ids = _model_and_ids()
    out = generate(model, variables, ids, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, variables, ids, max_new_tokens=-1)


def test_warm_cache_prefill_poisons_not_silently_wrong():
    """A second multi-token (prefill-style) call on a warm cache cannot be
    answered correctly by the fast path; it must yield NaN, not plausible
    garbage."""
    import jax.numpy as jnp

    model, variables, ids = _model_and_ids()
    dm = model.clone(decode=True)
    _, mut = dm.apply(
        {"params": variables["params"]}, ids, train=False, mutable=["cache"]
    )
    logits2, _ = dm.apply(
        {"params": variables["params"], "cache": mut["cache"]}, ids,
        train=False, mutable=["cache"],
    )
    assert bool(jnp.isnan(logits2).all())


def test_top_k_sampling_stays_in_top_k():
    """With top_k=1, sampling at any temperature degenerates to greedy."""
    model, variables, ids = _model_and_ids(seed=3)
    out_k1 = generate(model, variables, ids, max_new_tokens=6,
                      temperature=1.5, top_k=1, rng=jax.random.PRNGKey(3))
    ref = _naive_greedy(model, variables, ids, 6)
    np.testing.assert_array_equal(np.asarray(out_k1), np.asarray(ref))


def test_generate_with_tensor_parallel_params():
    """Distributed inference: generation runs unchanged on TP-sharded
    params (the decode program inherits the placements; XLA inserts the
    tensor-axis collectives) and reproduces the unsharded tokens."""
    from ml_trainer_tpu.parallel import create_mesh, rules_for, shard_params

    # Exact equality is valid on the simulated CPU mesh (deterministic
    # reductions); on real multi-chip hardware compare logits with a
    # tolerance instead — greedy argmax can flip on near-ties.
    model, variables, ids = _model_and_ids(seed=5)
    ref = generate(model, variables, ids, max_new_tokens=8)
    mesh = create_mesh({"tensor": 2}, devices=jax.devices()[:2])
    sharded = shard_params(variables["params"], mesh, rules_for("gpt2", "tp"))
    out = generate(model, {"params": sharded}, ids, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_beam_search_k1_equals_greedy():
    from ml_trainer_tpu.generate import beam_search

    model, variables, ids = _model_and_ids(seed=6)
    ref = _naive_greedy(model, variables, ids, 6)
    out = beam_search(model, variables, ids, max_new_tokens=6, num_beams=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def _seq_logprob(model, variables, full_ids, prompt_len):
    logits = model.apply(variables, full_ids, train=False)
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    total = 0.0
    for t in range(prompt_len, full_ids.shape[1]):
        total += float(logprobs[0, t - 1, int(full_ids[0, t])])
    return total


def test_beam_search_scores_at_least_greedy():
    """With several beams the returned sequence's log-probability should
    beat or match greedy's (not a theorem, but holds on this fixed seed —
    the point is beams explore beyond the greedy path)."""
    from ml_trainer_tpu.generate import beam_search

    model, variables, ids = _model_and_ids(seed=11, b=1, p=4)
    greedy_out = generate(model, variables, ids, max_new_tokens=5)
    beam_out = beam_search(model, variables, ids, max_new_tokens=5,
                           num_beams=8)
    lp_greedy = _seq_logprob(model, variables, greedy_out, 4)
    lp_beam = _seq_logprob(model, variables, beam_out, 4)
    assert lp_beam >= lp_greedy - 1e-4, (lp_beam, lp_greedy)


def test_beam_search_validates_args():
    from ml_trainer_tpu.generate import beam_search

    model, variables, ids = _model_and_ids()
    with pytest.raises(ValueError, match="num_beams"):
        beam_search(model, variables, ids, max_new_tokens=4, num_beams=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        beam_search(model, variables, ids, max_new_tokens=0)


def test_generate_from_loss_chunk_model():
    """The decode clone carries training-only attrs (loss_chunk) along;
    generation must keep using the logits path regardless."""
    import jax
    import numpy as np

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model

    m = get_model("gpt2_tiny", max_len=64, loss_chunk=16)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    out = generate(m, variables, np.ones((2, 8), np.int32), max_new_tokens=4)
    assert out.shape == (2, 12)


def test_generate_ragged_matches_per_length_generate():
    """Bucketed ragged generation must agree with running each length
    group through generate directly, and preserve input order."""
    import jax
    import numpy as np

    from ml_trainer_tpu.generate import generate, generate_ragged
    from ml_trainer_tpu.models import get_model

    m = get_model("gpt2_tiny", max_len=64)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    prompts = [
        np.asarray([5, 6, 7], np.int32),
        np.asarray([9, 10, 11, 12, 13], np.int32),
        np.asarray([1, 2, 3], np.int32),
    ]
    outs = generate_ragged(m, variables, prompts, max_new_tokens=4)
    assert [len(o) for o in outs] == [7, 9, 7]
    # Order preserved: each row equals generating its OWN length batch.
    ref3 = generate(
        m, variables,
        np.stack([prompts[0], prompts[2]]), max_new_tokens=4,
    )
    np.testing.assert_array_equal(outs[0], ref3[0])
    np.testing.assert_array_equal(outs[2], ref3[1])
    ref5 = generate(m, variables, prompts[1][None], max_new_tokens=4)
    np.testing.assert_array_equal(outs[1], ref5[0])


def test_generate_ragged_default_rng_folds_per_bucket():
    """With temperature > 0 and NO rng given, buckets must still draw
    independent key streams: the default rng is materialized inside
    generate_ragged so the per-bucket fold_in applies (omitting it would
    hand every bucket generate()'s identical PRNGKey(0) default).
    Pinned by equivalence: rng=None == rng=PRNGKey(0) explicitly."""
    import jax
    import numpy as np

    from ml_trainer_tpu.generate import generate_ragged
    from ml_trainer_tpu.models import get_model

    m = get_model("gpt2_tiny", max_len=64)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    prompts = [
        np.asarray([5, 6, 7], np.int32),
        np.asarray([9, 10, 11, 12, 13], np.int32),
    ]
    default = generate_ragged(
        m, variables, prompts, max_new_tokens=4, temperature=0.9
    )
    explicit = generate_ragged(
        m, variables, prompts, max_new_tokens=4, temperature=0.9,
        rng=jax.random.PRNGKey(0),
    )
    for a, b in zip(default, explicit):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_generate_ragged_pads_batch_to_power_of_two():
    """A group of 3 same-length prompts runs as a padded batch of 4; the
    real rows must match the unpadded batch result and no padding row
    leaks out.  Empty prompts are rejected up front."""
    import jax
    import numpy as np
    import pytest

    from ml_trainer_tpu.generate import generate, generate_ragged
    from ml_trainer_tpu.models import get_model

    m = get_model("gpt2_tiny", max_len=64)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    rows = [np.asarray([i + 1, i + 2, i + 3, i + 4], np.int32)
            for i in range(3)]
    outs = generate_ragged(m, variables, rows, max_new_tokens=3)
    assert len(outs) == 3 and all(len(o) == 7 for o in outs)
    ref = generate(m, variables, np.stack(rows + [rows[0]]),
                   max_new_tokens=3)
    for o, r in zip(outs, ref[:3]):
        np.testing.assert_array_equal(o, r)

    with pytest.raises(ValueError, match="non-empty"):
        generate_ragged(
            m, variables, [np.asarray([], np.int32)], max_new_tokens=2
        )


def test_top_p_nucleus_sampling():
    """top_p must restrict draws to the nucleus: with a distribution where
    one token holds most of the mass, a tight top_p collapses sampling to
    argmax; top_p=1.0 leaves the distribution unchanged (same draws as
    unfiltered sampling at the same rng)."""
    m = get_model("gpt2_tiny", max_len=64)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    prompt = np.ones((2, 8), np.int32)
    rng = jax.random.PRNGKey(7)

    greedy = generate(m, variables, prompt, max_new_tokens=6)
    tight = generate(m, variables, prompt, max_new_tokens=6,
                     temperature=0.05, top_p=1e-6, rng=rng)
    # Nucleus of ~one token at near-zero temperature == greedy path.
    np.testing.assert_array_equal(tight, greedy)

    full = generate(m, variables, prompt, max_new_tokens=6,
                    temperature=1.0, top_p=1.0, rng=rng)
    plain = generate(m, variables, prompt, max_new_tokens=6,
                     temperature=1.0, rng=rng)
    np.testing.assert_array_equal(full, plain)

    with pytest.raises(ValueError, match="top_p"):
        generate(m, variables, prompt, max_new_tokens=2,
                 temperature=1.0, top_p=0.0)


def test_eos_stops_row_and_pads_tail():
    """A row that emits eos keeps its static shape; positions after eos
    are pad_token_id, and rows that never hit eos are unaffected."""
    m = get_model("gpt2_tiny", max_len=64)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       np.zeros((1, 8), np.int32), train=False)
    # Distinct rows so one can hit "eos" while the other does not.
    prompt = np.stack([
        np.arange(1, 9, dtype=np.int32),
        np.arange(101, 109, dtype=np.int32),
    ])
    base = generate(m, variables, prompt, max_new_tokens=8)
    first_row_new = np.asarray(base[0, 8:])
    second_row_new = np.asarray(base[1, 8:])
    # eos := the first row's first new token, chosen to be absent from the
    # second row's continuation (guaranteed here, asserted to be safe).
    eos = int(first_row_new[0])
    assert eos not in second_row_new, "pick different seeds for this test"
    out = generate(m, variables, prompt, max_new_tokens=8,
                   eos_token_id=eos, pad_token_id=99)
    np.testing.assert_array_equal(np.asarray(out[0, 8:9]), [eos])
    np.testing.assert_array_equal(
        np.asarray(out[0, 9:]), np.full(7, 99)
    )
    # The unfinished row matches the unconstrained run exactly.
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(base[1]))

    import pytest as _pytest

    with _pytest.raises(ValueError, match="eos_token_id"):
        generate(m, variables, prompt, max_new_tokens=2,
                 eos_token_id=50_000)
