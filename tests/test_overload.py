"""Overload survival + chaos-proofed migration (serving/overload.py,
serving/autoscaler.py, the hardened router).

Ground truth stays ``generate()`` and the byte-identity contract: every
degradation rung acts at admission only, so a request already streaming
when a rung engages finishes byte-identical to its un-degraded prefix;
shed requests get the STRUCTURED 503 + retry_after, never a hang.
Around that core: breaker/quantile units, CRC-verified migration with
a bit-flipped payload, fault-injected corrupt adoption retrying on a
fallback candidate, health-poll flap damping, deadline budgets
decrementing across redistributes, hedged prefills (winner cancels
loser), autoscaler repair/hysteresis, and role reassignment draining
through the migration machinery.
"""

import time

import jax
import numpy as np
import pytest

from ml_trainer_tpu.generate import generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.resilience import faults
from ml_trainer_tpu.serving import (
    Autoscaler,
    AutoscalerConfig,
    CircuitBreaker,
    DegradationConfig,
    DegradationLadder,
    MigrationCorrupt,
    OverloadShed,
    RollingQuantile,
    Router,
    Server,
    transfer,
)
from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.scheduler import Request

PS = 8  # page size (max_len=64 -> 8 pages per slot)


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, n):
    return np.asarray(
        np.random.default_rng(seed).integers(0, 1024, n), np.int32
    )


# ------------------------------------------------------------- units


def test_circuit_breaker_state_machine():
    """closed -K failures-> open -cooldown-> half-open (ONE probe) ->
    closed on success / re-open on failure."""
    t = [0.0]
    b = CircuitBreaker(threshold=2, cooldown_s=5.0, clock=lambda: t[0])
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"       # one failure is not an outage
    b.record_failure()
    assert b.state == "open" and not b.allow()
    t[0] = 4.9
    assert not b.allow()             # cooldown not elapsed
    t[0] = 5.1
    assert b.state == "half_open"
    assert b.allow()                 # the single probe
    assert not b.allow()             # second caller blocked
    b.record_failure("probe died")
    assert b.state == "open"
    t[0] = 10.3
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b.allow()
    assert [tr["to"] for tr in b.transitions] == [
        "open", "half_open", "open", "half_open", "closed",
    ]


def test_rolling_quantile_floor_and_window():
    q = RollingQuantile(window=16, min_samples=4, default=2.5)
    assert q.quantile(0.99) == 2.5   # cold: the default, never 0
    for v in (0.1, 0.2, 0.3, 0.4):
        q.observe(v)
    assert q.quantile(0.99) == pytest.approx(0.4)
    assert q.quantile(0.5) == pytest.approx(0.3)  # nearest-rank
    for _ in range(16):
        q.observe(1.0)               # window slides: old values age out
    assert q.quantile(0.5) == pytest.approx(1.0)


def test_ladder_validation_and_history():
    srv_calls = []

    class _FakeServer:
        def set_degradation(self, level, cfg):
            srv_calls.append(level)

        def shed_queued(self, below, retry_after, cause=""):
            srv_calls.append(("shed", below))
            return 2

    with pytest.raises(ValueError, match="clamp_tokens"):
        DegradationConfig(clamp_tokens=0)
    ladder = DegradationLadder(
        [_FakeServer()], DegradationConfig(shed_below_priority=1)
    )
    assert ladder.level == 0 and ladder.rung == "normal"
    ladder.step_up("burn")
    ladder.set_level(4, "burn worse")
    assert ladder.rung == "shed_queued"
    assert ("shed", 1) in srv_calls      # rung-4 entry sheds the backlog
    ladder.step_down()
    snap = ladder.snapshot()
    assert snap["level"] == 3 and snap["transitions"] == 3
    assert snap["shed_total"] == 2
    assert [r["to"] for r in snap["history"]] == [1, 4, 3]


# ----------------------------------------- degradation byte identity


def test_clamp_rung_spares_running_stream(model_and_vars):
    """Rung 1 engages while a request streams: the RUNNING request
    keeps its full budget and finishes byte-identical to generate();
    a fresh request gets the clamped budget — and its (shorter) output
    is byte-identical to its un-degraded prefix."""
    model, variables = model_and_vars
    pA, pB = _prompt(0, 9), _prompt(1, 7)
    refA = np.asarray(generate(model, variables, pA[None], 24))[0]
    refB = np.asarray(generate(model, variables, pB[None], 24))[0]
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        ladder = DegradationLadder(
            [server], DegradationConfig(clamp_tokens=5)
        )
        sA = server.submit(pA, 24)
        deadline = time.monotonic() + 60
        while len(sA.tokens) < 3:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        ladder.set_level(1, "test burn")
        sB = server.submit(pB, 24)
        outA = np.asarray(sA.result(timeout=120))
        outB = np.asarray(sB.result(timeout=120))
    np.testing.assert_array_equal(outA, refA)       # running: undegraded
    assert outB.size == pB.size + 5                 # fresh: clamped
    np.testing.assert_array_equal(outB, refB[: outB.size])


def test_spec_off_mid_stream_stays_byte_identical(model_and_vars):
    """Rung 2 (spec off) engages mid-stream: greedy speculative decode
    equals vanilla greedy by construction, so the stream crossing the
    transition finishes byte-identical to generate() — and the engine
    really did switch to the vanilla step."""
    model, variables = model_and_vars
    p = _prompt(2, 9)
    ref = np.asarray(generate(model, variables, p[None], 20))[0]
    with Server(model, variables, max_batch=2, kv_page_size=PS,
                spec_k=4) as server:
        ladder = DegradationLadder([server])
        s = server.submit(p, 20)
        deadline = time.monotonic() + 60
        while len(s.tokens) < 4:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        ladder.set_level(2, "test burn")
        assert server.engine.spec_enabled is False
        spec_steps_at_switch = server.metrics.snapshot()[
            "spec_steps_total"
        ]
        out = np.asarray(s.result(timeout=120))
        # At most the ONE in-flight verify step finishes after the rung
        # engages; every later step is the vanilla program.
        assert server.metrics.snapshot()["spec_steps_total"] <= \
            spec_steps_at_switch + 1
    np.testing.assert_array_equal(out, ref)


def test_hits_only_rung_sheds_misses_structured(model_and_vars):
    """Rung 3: a fresh prefix-cache MISS is shed with OverloadShed +
    retry_after; a request sharing a cached prefix still serves."""
    model, variables = model_and_vars
    shared = _prompt(3, 2 * PS + 4)  # two full blocks + suffix
    miss = _prompt(4, 20)
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        server.complete(shared, 4, timeout=120)     # prime the cache
        ladder = DegradationLadder(
            [server], DegradationConfig(retry_after_s=1.5)
        )
        ladder.set_level(3, "test burn")
        hit_out = server.complete(
            np.concatenate([shared[: 2 * PS], _prompt(5, 4)]), 3,
            timeout=120,
        )
        assert hit_out.size == 2 * PS + 4 + 3
        with pytest.raises(OverloadShed, match="hits_only") as ei:
            server.complete(miss, 4, timeout=120)
        assert ei.value.retry_after == pytest.approx(1.5)
        assert server.metrics.snapshot()["requests_shed"] == 1


def test_shed_queued_rung_keeps_priority_traffic(model_and_vars):
    """Rung 4 entry sheds LOW-priority queued requests (structured,
    retry_after) while higher-priority queued work survives and the
    running stream finishes undegraded; fresh low-priority submissions
    are refused at admission.  Rungs are cumulative, so the surviving
    queued request must be a prefix-cache HIT to clear rung 3 — it
    shares the running request's cached prompt blocks."""
    model, variables = model_and_vars
    pLong = _prompt(6, 2 * PS + 4)                  # 2 full cached blocks
    pLo = _prompt(7, 8)
    pHi = np.concatenate([pLong[: 2 * PS], _prompt(8, 4)])
    refLong = np.asarray(generate(model, variables, pLong[None], 24))[0]
    refHi = np.asarray(generate(model, variables, pHi[None], 4))[0]
    with Server(model, variables, max_batch=1, kv_page_size=PS) as server:
        sLong = server.submit(pLong, 24)            # occupies the slot
        deadline = time.monotonic() + 60
        while len(sLong.tokens) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        sLo = server.submit(pLo, 4, priority=0)     # queued
        sHi = server.submit(pHi, 4, priority=1)     # queued, prioritized
        ladder = DegradationLadder(
            [server], DegradationConfig(retry_after_s=2.0,
                                        shed_below_priority=1)
        )
        ladder.set_level(4, "test burn")
        with pytest.raises(OverloadShed, match="shed") as ei:
            sLo.result(timeout=120)
        assert ei.value.retry_after == pytest.approx(2.0)
        with pytest.raises(OverloadShed, match="priority"):
            server.submit(_prompt(9, 8), 4, priority=0)
        np.testing.assert_array_equal(
            np.asarray(sLong.result(timeout=120)), refLong
        )
        np.testing.assert_array_equal(
            np.asarray(sHi.result(timeout=120)), refHi
        )
        assert ladder.snapshot()["shed_total"] == 1


def test_shed_maps_to_http_503_with_retry_after(model_and_vars):
    """The structured refusal over the wire: 503, JSON body naming the
    rung, retry_after in body AND Retry-After header."""
    import json
    import urllib.error
    import urllib.request

    model, variables = model_and_vars
    with Server(model, variables, max_batch=2, kv_page_size=PS) as server:
        DegradationLadder(
            [server], DegradationConfig(retry_after_s=3.0)
        ).set_level(4, "test")
        host, port = server.serve_http(port=0)
        body = json.dumps({
            "prompt": [int(t) for t in _prompt(10, 8)],
            "max_new_tokens": 4,
        }).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/v1/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        err = ei.value
        assert err.code == 503
        assert err.headers["Retry-After"] == "3"
        payload = json.loads(err.read())
        assert "shed" in payload["error"]
        assert payload["retry_after"] == pytest.approx(3.0)


# -------------------------------------------------- CRC'd migration


def test_migration_payload_bit_flip_is_refused(model_and_vars):
    """A bit-flipped serialized payload raises the structured
    MigrationCorrupt (satellite regression test), and a tampered
    in-memory export is refused at import before any page scatters."""
    model, variables = model_and_vars
    eng = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    req = Request(prompt=_prompt(11, 10), max_new_tokens=4)
    eng.admit(req, 0)
    exp = eng.export_slot(0)
    assert exp.crc32s and len(exp.crc32s) == len(exp.layers)
    payload = transfer.to_bytes(exp)
    # Clean round trip verifies.
    transfer.from_bytes(payload)
    flipped = bytearray(payload)
    flipped[len(flipped) // 2] ^= 0x10
    with pytest.raises(MigrationCorrupt, match="corrupt"):
        transfer.from_bytes(bytes(flipped))
    # In-memory tamper: import refuses before binding anything.
    exp.layers[0] = exp.layers[0].copy()
    exp.layers[0].flat[0] += 1
    dst = SlotDecodeEngine(model, variables, max_batch=2, kv_page_size=PS)
    cont = Request(prompt=exp.prompt, max_new_tokens=4)
    with pytest.raises(MigrationCorrupt, match="layer 0"):
        dst.import_slot(cont, 0, exp)
    assert dst.pool.slot_page_count(0) == 0
    assert dst.active_count() == 0


def test_corrupt_migration_retries_on_fallback_candidate(model_and_vars):
    """The migration_corrupt fault flips one payload in flight: the CRC
    gate refuses it, the router retries the adoption on a fallback
    decode candidate with a fresh serialization, and the stream stays
    byte-identical."""
    model, variables = model_and_vars
    p = _prompt(12, 9)
    ref = np.asarray(generate(model, variables, p[None], 14))[0]
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        with faults.injected("migration_corrupt"):
            out = np.asarray(router.complete(p, 14, timeout=180))
        snap = router.snapshot()
    np.testing.assert_array_equal(out, ref)
    assert snap["migrations_corrupt_total"] == 1
    assert snap["migrations_total"] >= 1


# ------------------------------------------------------ flap damping


def test_single_dropped_health_poll_causes_no_redistribution(
        model_and_vars):
    """The satellite pin: ONE failed/dropped poll (healthz_flap) is
    damped — the replica stays in the pool and nothing redistributes."""
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        # Sorted fleet: decode0 -> index 0.
        assert router.replica("decode0").server.replica_index == 0
        s = router.submit(_prompt(13, 8), 16)
        with faults.injected("healthz_flap@host=0"):
            time.sleep(4 * router._health_interval)
            out = np.asarray(s.result(timeout=180))
        snap = router.snapshot()
        assert router.replica("decode0").healthy
    assert out.size == 8 + 16
    assert snap["redistributes_total"] == 0
    assert snap["flaps_damped_total"] >= 1
    assert snap["replica_healthy"]["decode0"] == 1


# ------------------------------------------------- deadline budgets


def test_deadline_budget_survives_placement_retries(model_and_vars):
    """The deadline satellite: when every replica dies mid-stream and
    placement keeps failing, the request expires AT its deadline —
    the remaining budget decrements across redistributes instead of
    spinning the full admission-retry window."""
    from ml_trainer_tpu.serving import DeadlineExceeded

    model, variables = model_and_vars
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS,
                      router_kwargs={"admission_retry_s": 30.0},
                      ) as router:
        s = router.submit(_prompt(14, 8), 40, deadline=2.0)
        deadline = time.monotonic() + 60
        while len(s.tokens) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        t0 = time.monotonic()
        router.kill_replica("prefill0")
        router.kill_replica("decode0")
        with pytest.raises(DeadlineExceeded):
            s.result(timeout=60)
        elapsed = time.monotonic() - t0
    # Expired near the (2s) deadline — nowhere near the 30s admission
    # retry window the un-fixed path would spin.
    assert elapsed < 10.0


def test_shadow_deadline_decrements(model_and_vars):
    """The per-attempt shadow carries the REMAINING budget, not the
    original: after time passes, a redistribute's shadow deadline is
    strictly smaller."""
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["both"],
                      max_batch=2, kv_page_size=PS) as router:
        creq = Request(prompt=_prompt(15, 6), max_new_tokens=4,
                       deadline=10.0)
        time.sleep(0.25)
        remaining = router._remaining_deadline(creq)
        shadow = router._shadow(creq, [], remaining)
        assert shadow.deadline < 10.0
        assert shadow.deadline == pytest.approx(remaining, abs=0.05)
        assert remaining <= 9.8


# ----------------------------------------------------- hedged prefill


def test_hedged_prefill_wins_and_cancels_loser(model_and_vars):
    """A slow prefill replica: after the rolling-p99 clock the router
    fires a duplicate on the OTHER prefill replica, the duplicate wins,
    the loser is cancelled, and the output is byte-identical."""
    model, variables = model_and_vars
    p = _prompt(16, 9)
    ref = np.asarray(generate(model, variables, p[None], 10))[0]
    with Router.build(model, variables,
                      roles=["prefill", "prefill", "decode"],
                      max_batch=2, kv_page_size=PS,
                      router_kwargs={"hedge_min_s": 0.05},
                      ) as router:
        # Warm the hedge clock so p99 is tiny and the floor dominates.
        for _ in range(12):
            router._first_result_lat.observe(0.01)
        # The affinity ring decides the primary: slow exactly it.
        key = router._affinity_key("default", p)
        primary = router._ring.place(
            key, {n: r for n, r in router.replicas.items()
                  if r.role == "prefill"},
        )
        idx = router.replica(primary).server.replica_index
        with faults.injected(f"replica_slow@step=1,host={idx},secs=3"):
            out = np.asarray(router.complete(p, 10, timeout=180))
        snap = router.snapshot()
        # The loser was withdrawn: nothing stays active anywhere.
        deadline = time.monotonic() + 30
        while any(
            r.server.engine.active_count()
            or r.server.scheduler.queue_depth()
            for r in router.replicas.values()
        ):
            assert time.monotonic() < deadline, "loser never cancelled"
            time.sleep(0.05)
    np.testing.assert_array_equal(out, ref)
    assert snap["hedges_total"] >= 1
    assert snap["hedge_wins_total"] >= 1


def test_unseeded_sampled_requests_never_hedge(model_and_vars):
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        greedy = Request(prompt=_prompt(17, 6), max_new_tokens=4)
        seeded = Request(prompt=_prompt(17, 6), max_new_tokens=4,
                         temperature=0.8, rng=7)
        unseeded = Request(prompt=_prompt(17, 6), max_new_tokens=4,
                           temperature=0.8)
        assert router._hedge_eligible(greedy)
        assert router._hedge_eligible(seeded)
        assert not router._hedge_eligible(unseeded)


# ------------------------------------------------------- autoscaler


def test_autoscaler_replaces_dead_replica(model_and_vars):
    """Repair rule: a replica death drops the decode fleet below its
    floor — the next tick adds a replacement (no hysteresis wait), and
    the fleet serves again."""
    model, variables = model_and_vars
    p = _prompt(18, 8)
    ref = np.asarray(generate(model, variables, p[None], 8))[0]
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        asc = Autoscaler(
            router,
            lambda role: Server(model, variables, max_batch=2,
                                kv_page_size=PS, role=role),
            AutoscalerConfig(min_decode=2),
        )
        assert asc.tick() is None            # healthy fleet: no action
        router.kill_replica("decode0")
        assert asc.tick() == "scale_up"
        assert "auto1" in router.replicas
        assert router.replica("auto1").role == "decode"
        out = np.asarray(router.complete(p, 8, timeout=180))
        summary = asc.summary()
    np.testing.assert_array_equal(out, ref)
    assert summary["counts"]["scale_up"] == 1
    assert summary["actions"][0]["cause"].startswith("decode fleet")


def test_autoscaler_hysteresis_cooldown_and_ladder(model_and_vars):
    """The control law, on a fake clock and a stubbed fleet view: burn
    must stay high for high_polls CONSECUTIVE ticks, actions respect
    the cooldown, at max_replicas the ladder steps up, and recovery
    walks the ladder back down before scaling down."""
    model, variables = model_and_vars
    with Router.build(model, variables, roles=["both"],
                      max_batch=2, kv_page_size=PS) as router:
        t = [0.0]
        asc = Autoscaler(
            router, lambda role: None,
            AutoscalerConfig(
                burn_high=2.0, burn_low=0.25, high_polls=2, low_polls=2,
                cooldown_s=4.0, max_replicas=1, role_flip=False,
                scale_down=False,
            ),
            clock=lambda: t[0],
        )
        burn = [5.0]

        def fake_fleet():
            reps = list(router.replicas.values())
            return {
                "now": t[0], "alive": reps, "total": len(reps),
                "prefill": reps, "decode": reps,
                "prefill_pressure": 4, "decode_pressure": 4,
                "burn": burn[0], "window_requests": 20,
            }

        asc._fleet = fake_fleet
        assert asc.tick() is None            # 1 high poll: hysteresis
        assert asc.tick() == "degrade"       # 2nd consecutive: rung 1
        assert router.ladder.level == 1
        assert asc.tick() is None            # cooldown holds the streak
        t[0] = 5.0
        assert asc.tick() == "degrade"       # cooldown over: rung 2
        assert router.ladder.level == 2
        burn[0] = 1.0                        # inside the band
        t[0] = 10.0
        assert asc.tick() is None            # streaks decay in-band
        burn[0] = 0.0                        # recovered
        assert asc.tick() is None            # 1 low poll
        assert asc.tick() == "undegrade"     # 2nd: rung back down
        assert router.ladder.level == 1
        t[0] = 15.0
        assert asc.tick() is None
        assert asc.tick() == "undegrade"
        assert router.ladder.level == 0


def test_role_reassignment_drains_through_migration(model_and_vars):
    """The role flip exports a busy replica's active slots through the
    migration machinery (streams keep flowing on the adopter, byte-
    identical) before the role changes."""
    model, variables = model_and_vars
    p = _prompt(19, 8)
    ref = np.asarray(generate(model, variables, p[None], 40))[0]
    # Built BEFORE the stream starts so the flip happens mid-stream.
    d2 = Server(model, variables, max_batch=2, kv_page_size=PS,
                role="decode")
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=PS) as router:
        s = router.submit(p, 40)
        deadline = time.monotonic() + 60
        while len(s.tokens) < 3:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        migrations_before = router.snapshot()["migrations_total"]
        router.add_replica("d2", d2)
        assert router.reassign_role("decode0", "prefill", timeout=30.0)
        assert router.replica("decode0").role == "prefill"
        assert router.replica("decode0").server.role == "prefill"
        out = np.asarray(s.result(timeout=180))
        snap = router.snapshot()
    np.testing.assert_array_equal(out, ref)
    # The evacuation itself moved KV (beyond the original admission).
    assert snap["migrations_total"] > migrations_before
    assert snap["redistributes_total"] == 0  # drained, not failed over
