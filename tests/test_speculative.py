"""Speculative decoding (ml_trainer_tpu/speculative.py + serving spec
mode).

The load-bearing property: greedy speculative output is BYTE-IDENTICAL
to vanilla ``generate()`` for any draft source and any K — the drafts
only decide how many tokens commit per verify step, never which.
Around that core: the windowed cache-append at unaligned offsets, the
n-gram drafter's lookup rules, rejection sampling at temperature > 0,
serving-engine spec mode with mid-stream joins, acceptance metrics, and
the no-recompilation guarantee at fixed K.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu.generate import _COMPILED, _cache_shapes, generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.speculative import (
    DraftModelDrafter,
    NgramDrafter,
    speculative_generate,
)


@pytest.fixture(scope="module")
def model_and_vars():
    model = get_model("gpt2_tiny", max_len=128)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


@pytest.fixture(scope="module")
def draft_and_vars():
    # Same 1024 vocab as gpt2_tiny, quarter the width and depth.
    model = get_model("gpt2_tiny", max_len=128, depth=1, embed_dim=64,
                      num_heads=2)
    variables = model.init(
        {"params": jax.random.PRNGKey(1)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    return model, variables


def _prompt(seed, b=2, p=7):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 1024, (b, p)), jnp.int32
    )


# ------------------------------------------------- windowed cache-append
def test_windowed_cache_append_at_unaligned_offsets(model_and_vars):
    """A multi-token window through the per-row decode path at an
    UNALIGNED dynamic offset must reproduce the full causal forward's
    logits exactly, and land its K/V at exactly positions
    [offset, offset+window)."""
    model, variables = model_and_vars
    params = variables["params"]
    dm = model.clone(decode=True)
    ids = _prompt(0, b=2, p=11)
    shapes = _cache_shapes(dm, 2, jnp.int32)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    # Prefill 7 tokens (scalar path), widen index leaves to per-row.
    _, mut = dm.apply(
        {"params": params, "cache": cache}, ids[:, :7],
        train=False, mutable=["cache"],
    )
    cache = jax.tree.map(
        lambda l: jnp.full((2,), 7, jnp.int32) if l.ndim == 0 else l,
        mut["cache"],
    )
    # Window of 4 tokens at the unaligned offset 7.
    logits_w, mut2 = dm.apply(
        {"params": params, "cache": cache}, ids[:, 7:11],
        train=False, mutable=["cache"],
    )
    ref = model.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(
        np.asarray(logits_w), np.asarray(ref[:, 7:11]), rtol=2e-5,
        atol=2e-5,
    )
    # K/V landed at positions 7..10 and nowhere else; indices advanced.
    for leaf in jax.tree.leaves(mut2["cache"]):
        if leaf.ndim == 1:
            np.testing.assert_array_equal(np.asarray(leaf), [11, 11])
        else:
            assert not np.allclose(np.asarray(leaf[:, :, 7:11]), 0.0)
            np.testing.assert_array_equal(
                np.asarray(leaf[:, :, 11:]), 0.0
            )


def test_windowed_append_per_row_distinct_offsets(model_and_vars):
    """Rows sitting at DIFFERENT positions write their windows at their
    own offsets — each row's logits match its own-length reference."""
    model, variables = model_and_vars
    params = variables["params"]
    dm = model.clone(decode=True)
    rng = np.random.default_rng(3)
    row0 = jnp.asarray(rng.integers(0, 1024, 9), jnp.int32)   # 5 + 4
    row1 = jnp.asarray(rng.integers(0, 1024, 7), jnp.int32)   # 3 + 4
    shapes = _cache_shapes(dm, 1, jnp.int32)

    def prefill(row, p):
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        _, mut = dm.apply(
            {"params": params, "cache": cache}, row[None, :p],
            train=False, mutable=["cache"],
        )
        return mut["cache"]

    c0, c1 = prefill(row0, 5), prefill(row1, 3)
    # Stack the two batch-1 caches into one 2-row slot cache with
    # per-row indices (5, 3).
    cache = jax.tree.map(
        lambda a, b: (
            jnp.concatenate([a, b]) if a.ndim else
            jnp.asarray([a, b], jnp.int32)
        ),
        c0, c1,
    )
    window = jnp.stack([row0[5:9], row1[3:7]])
    logits_w, _ = dm.apply(
        {"params": params, "cache": cache}, window,
        train=False, mutable=["cache"],
    )
    ref0 = model.apply({"params": params}, row0[None], train=False)
    ref1 = model.apply({"params": params}, row1[None], train=False)
    np.testing.assert_allclose(
        np.asarray(logits_w[0]), np.asarray(ref0[0, 5:9]),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(logits_w[1]), np.asarray(ref1[0, 3:7]),
        rtol=2e-5, atol=2e-5,
    )


# ------------------------------------------------------- n-gram drafter
def test_ngram_drafter_lookup_rules():
    d = NgramDrafter(k=3, n=2)
    # Last bigram (7, 8) matched earlier; continuation 9, 1, 2 follows.
    hist = np.asarray([7, 8, 9, 1, 2, 3, 7, 8], np.int32)
    np.testing.assert_array_equal(d.draft_one(hist), [9, 1, 2])
    # Most RECENT match wins over the first.
    hist2 = np.asarray([5, 6, 1, 5, 6, 2, 5, 6], np.int32)
    assert d.draft_one(hist2)[0] == 2
    # No match at any n: repeat the last token.
    hist3 = np.asarray([1, 2, 3, 4], np.int32)
    np.testing.assert_array_equal(d.draft_one(hist3), [4, 4, 4])
    # Short continuation pads with its own last token.
    hist4 = np.asarray([5, 4, 5, 4], np.int32)
    np.testing.assert_array_equal(d.draft_one(hist4), [5, 4, 4])


def test_ngram_drafter_validates():
    with pytest.raises(ValueError, match="k must be"):
        NgramDrafter(k=0)
    with pytest.raises(ValueError, match="min_n"):
        NgramDrafter(k=2, n=2, min_n=3)


# -------------------------------------------- greedy output identity
@pytest.mark.parametrize("k", [2, 4, 8])
def test_greedy_spec_byte_identical_lookup(model_and_vars, k):
    """The acceptance property, lookup drafter: greedy speculative ==
    vanilla generate, byte for byte, K ∈ {2, 4, 8}."""
    model, variables = model_and_vars
    ids = _prompt(1, b=3)
    ref = np.asarray(generate(model, variables, ids, 40))
    out = speculative_generate(model, variables, ids, 40, draft_k=k)
    np.testing.assert_array_equal(np.asarray(out), ref)


@pytest.mark.parametrize("k", [2, 4, 8])
def test_greedy_spec_byte_identical_draft_model(
    model_and_vars, draft_and_vars, k
):
    """Same property, small-draft-model drafter."""
    model, variables = model_and_vars
    dmod, dvars = draft_and_vars
    ids = _prompt(2, b=2)
    ref = np.asarray(generate(model, variables, ids, 32))
    out = speculative_generate(
        model, variables, ids, 32, draft_k=k, drafter=dmod,
        draft_variables=dvars,
    )
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_spec_generate_via_generate_kwarg(model_and_vars):
    """generate(spec_k=...) routes through the speculative path and
    keeps the output contract."""
    model, variables = model_and_vars
    ids = _prompt(4)
    ref = np.asarray(generate(model, variables, ids, 24))
    out = generate(model, variables, ids, 24, spec_k=4)
    np.testing.assert_array_equal(np.asarray(out), ref)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, variables, ids, 8, spec_k=4, top_k=5)


def test_spec_eos_matches_generate(model_and_vars):
    """EOS semantics under speculation: the row stops at EOS and pads
    the tail exactly like generate()."""
    model, variables = model_and_vars
    ids = _prompt(5, b=2)
    base = np.asarray(generate(model, variables, ids, 16))
    eos = int(base[0, ids.shape[1] + 2])  # a token a few steps in
    ref = np.asarray(generate(model, variables, ids, 16,
                              eos_token_id=eos, pad_token_id=99))
    out = speculative_generate(model, variables, ids, 16, draft_k=4,
                               eos_token_id=eos, pad_token_id=99)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_spec_stats_and_acceptance_accounting(model_and_vars):
    model, variables = model_and_vars
    ids = _prompt(6)
    out, stats = speculative_generate(
        model, variables, ids, 30, draft_k=4, return_stats=True
    )
    assert out.shape == (2, 7 + 30)
    assert stats["verify_steps"] > 0
    assert len(stats["accept_hist"]) == 5
    assert stats["drafted_tokens"] == sum(stats["accept_hist"]) * 4
    assert 0.0 <= stats["acceptance_rate"] <= 1.0
    assert 1.0 <= stats["tokens_per_step"] <= 5.0


def test_spec_sampled_runs_and_in_range(model_and_vars):
    """temperature > 0 uses rejection sampling: same distribution, not
    the same stream — assert shape/vocab-range and seed determinism."""
    model, variables = model_and_vars
    ids = _prompt(7)
    a = np.asarray(speculative_generate(
        model, variables, ids, 20, draft_k=4, temperature=0.8,
        rng=jax.random.PRNGKey(5),
    ))
    b = np.asarray(speculative_generate(
        model, variables, ids, 20, draft_k=4, temperature=0.8,
        rng=jax.random.PRNGKey(5),
    ))
    np.testing.assert_array_equal(a, b)  # same seed, same stream
    assert a.shape == (2, 27) and a.min() >= 0 and a.max() < 1024
    np.testing.assert_array_equal(a[:, :7], np.asarray(ids))


def test_spec_validates_args(model_and_vars, draft_and_vars):
    model, variables = model_and_vars
    ids = _prompt(8)
    with pytest.raises(ValueError, match="draft_k"):
        speculative_generate(model, variables, ids, 8, draft_k=0)
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(model, variables, ids, 10_000, draft_k=4)
    with pytest.raises(ValueError, match="draft_variables"):
        speculative_generate(model, variables, ids, 8, draft_k=4,
                             drafter=model)
    # Vocab-incompatible draft model is rejected up front.
    wrong = get_model("gpt2_tiny", max_len=128, vocab_size=512)
    wvars = wrong.init({"params": jax.random.PRNGKey(2)},
                       np.zeros((1, 4), np.int32), train=False)
    with pytest.raises(ValueError, match="vocab_size"):
        speculative_generate(model, variables, ids, 8, draft_k=4,
                             drafter=wrong, draft_variables=wvars)


def test_registry_draft_pairing():
    from ml_trainer_tpu.models.registry import suggested_draft

    target = get_model("gpt2_mini")
    draft = suggested_draft("gpt2_mini")
    assert draft.vocab_size == target.vocab_size
    DraftModelDrafter(draft, {"params": {}}).check_compatible(target)
    with pytest.raises(ValueError, match="n-gram"):
        suggested_draft("bert_tiny")


# --------------------------------------------------- serving spec mode
def test_serving_spec_mid_stream_join_byte_identical(model_and_vars):
    """The serving acceptance scenario: spec mode, requests joining a
    RUNNING speculative decode at arbitrary boundaries — greedy rows
    byte-identical to standalone generate(), acceptance counters live."""
    from ml_trainer_tpu.serving import Server

    model, variables = model_and_vars
    pA = np.asarray(np.random.default_rng(20).integers(0, 1024, 5),
                    np.int32)
    pB = np.asarray(np.random.default_rng(21).integers(0, 1024, 3),
                    np.int32)
    pC = np.asarray(np.random.default_rng(22).integers(0, 1024, 6),
                    np.int32)
    refA = np.asarray(generate(model, variables, pA[None], 24))[0]
    refB = np.asarray(generate(model, variables, pB[None], 9))[0]
    refC = np.asarray(generate(model, variables, pC[None], 7))[0]
    with Server(model, variables, max_batch=3, spec_k=4) as server:
        sA = server.submit(pA, 24)
        next(iter(sA))  # A is actively decoding when B and C join
        sB = server.submit(pB, 9)
        sC = server.submit(pC, 7)
        outA = sA.result(timeout=120)
        outB = sB.result(timeout=120)
        outC = sC.result(timeout=120)
        snap = server.metrics.snapshot()
    np.testing.assert_array_equal(outA, refA)
    np.testing.assert_array_equal(outB, refB)
    np.testing.assert_array_equal(outC, refC)
    assert snap["max_active_slots"] >= 2
    assert snap["spec_steps_total"] > 0
    assert snap["spec_drafted_tokens"] > 0
    assert sum(snap["spec_accept_hist"].values()) > 0
    assert 0.0 <= snap["spec_acceptance_rate"] <= 1.0
    assert snap["spec_tokens_per_step"] >= 1.0


def test_serving_spec_draft_model_and_slot_reuse(
    model_and_vars, draft_and_vars
):
    """Draft-model drafter in the engine: more requests than slots, so
    slots recycle mid-run; every output byte-identical."""
    from ml_trainer_tpu.serving import Server

    model, variables = model_and_vars
    dmod, dvars = draft_and_vars
    prompts = [
        np.asarray(np.random.default_rng(30 + i).integers(0, 1024, 3 + i),
                   np.int32)
        for i in range(5)
    ]
    refs = [
        np.asarray(generate(model, variables, p[None], 8 + i))[0]
        for i, p in enumerate(prompts)
    ]
    with Server(model, variables, max_batch=2, spec_k=3,
                drafter=dmod, draft_variables=dvars) as server:
        streams = [server.submit(p, 8 + i)
                   for i, p in enumerate(prompts)]
        outs = [s.result(timeout=120) for s in streams]
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)


def test_serving_spec_no_recompilation_across_ragged_traffic(
    model_and_vars
):
    """The static-shape guarantee: after a warm-up wave, a second wave
    of DIFFERENT ragged prompts/budgets at the same fixed K compiles
    NOTHING new — the compiled-program count stays constant."""
    from ml_trainer_tpu.serving import Server

    model, variables = model_and_vars

    def wave(server, seed0):
        for i in range(6):
            p = np.asarray(
                np.random.default_rng(seed0 + i).integers(
                    0, 1024, 3 + (i % 4)
                ),
                np.int32,
            )
            server.complete(p, 4 + (i % 5), timeout=120)

    with Server(model, variables, max_batch=2, spec_k=4) as server:
        wave(server, 100)
        n_warm = len(_COMPILED._data)
        wave(server, 200)
        n_after = len(_COMPILED._data)
    assert n_after == n_warm, (
        f"ragged spec traffic at fixed K compiled "
        f"{n_after - n_warm} new program(s)"
    )


def test_serving_spec_request_counters_and_validation(model_and_vars):
    from ml_trainer_tpu.serving import Server

    model, variables = model_and_vars
    p = np.asarray(np.random.default_rng(40).integers(0, 1024, 4),
                   np.int32)
    with Server(model, variables, max_batch=1, spec_k=4) as server:
        stream = server.submit(p, 12)
        stream.result(timeout=120)
        req = stream.request
        assert req.spec_steps > 0
        assert req.spec_accepted_tokens >= 0
        # max_len guard now includes the spec_k slack.
        with pytest.raises(ValueError, match="spec_k"):
            server.submit(p, 128 - 4)
