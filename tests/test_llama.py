"""Llama-family (RMSNorm + RoPE + GQA + SwiGLU) — beyond the north-star
zoo: the modern LM architecture on the same TPU-first machinery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.models.llama import apply_rope

pytestmark = pytest.mark.slow


def test_rope_preserves_norm_and_relative_phase():
    """Rotations preserve per-pair norms, and shifting BOTH q and k by the
    same offset leaves their inner products unchanged (the relative-
    position property RoPE exists for)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 2, 8, 16)), jnp.float32)
    r0 = apply_rope(x, jnp.arange(8))
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r0), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5,
    )
    q = jnp.asarray(rng.normal(size=(1, 1, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 4, 16)), jnp.float32)
    def scores(offset):
        qr = apply_rope(q, jnp.arange(4) + offset)
        kr = apply_rope(k, jnp.arange(4) + offset)
        return np.einsum("bhqd,bhkd->bhqk", np.asarray(qr), np.asarray(kr))
    np.testing.assert_allclose(scores(0), scores(17), atol=1e-4)


def test_llama_forward_shapes_and_gqa_params():
    model = get_model("llama_tiny")
    ids = jnp.ones((2, 16), jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, ids, train=False)
    out = model.apply(variables, ids, train=False)
    assert out.shape == (2, 16, 1024)
    attn = variables["params"]["block0"]["attn"]
    # GQA: k/v projections are Hkv/H the width of q (4 heads vs 2 kv).
    assert attn["q"]["kernel"].shape == (64, 64)
    assert attn["k"]["kernel"].shape == (64, 32)
    assert attn["v"]["kernel"].shape == (64, 32)
    # No biases anywhere (Llama arrangement).
    assert not any(
        "bias" in k for k in jax.tree_util.tree_flatten_with_path(
            variables["params"]
        )[0] for k in [str(k)]
    )


def test_llama_trains_and_chunked_loss_matches_dense(tmp_path):
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=0)
    common = dict(
        datasets=(ds, ds), epochs=2, batch_size=8, metric=None,
        optimizer="adamw", lr=0.01, seed=3,
    )
    dense = Trainer(get_model("llama_tiny"),
                    model_dir=str(tmp_path / "d"), **common)
    dense.fit()
    assert all(np.isfinite(v) for v in dense.train_losses)
    chunked = Trainer(get_model("llama_tiny", loss_chunk=8),
                      model_dir=str(tmp_path / "c"), **common)
    chunked.fit()
    np.testing.assert_allclose(
        dense.train_losses, chunked.train_losses, rtol=1e-4
    )


def test_llama_greedy_decode_matches_full_forward():
    """The GQA + RoPE KV cache must reproduce the dense model exactly:
    greedy generate() == argmax over repeated full forwards."""
    from ml_trainer_tpu.generate import generate

    model = get_model("llama_tiny")
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(1, 1024, size=(2, 7)), jnp.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(1)}, prompt, train=False
    )
    out = generate(model, variables, prompt, max_new_tokens=6)
    # Naive reference: full forward each step, argmax of the last position.
    seq = prompt
    for _ in range(6):
        logits = model.apply(variables, seq, train=False)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(seq.dtype)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_llama_tensor_parallel_matches_dp(tmp_path):
    """dp=4 x tp=2 llama training: q/gate/up kernels land column-sharded,
    down row-sharded, and the trajectory matches pure DP."""
    from jax.sharding import PartitionSpec as P

    from ml_trainer_tpu.parallel import rules_for

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=2)
    common = dict(
        datasets=(ds, ds), epochs=1, batch_size=16, metric=None,
        optimizer="adamw", lr=0.01, seed=6, is_parallel=True, backend="cpu",
    )
    dp = Trainer(get_model("llama_tiny"),
                 model_dir=str(tmp_path / "dp"), **common)
    dp.fit()
    tp = Trainer(
        get_model("llama_tiny"), model_dir=str(tmp_path / "tp"),
        mesh_shape={"data": 4, "tensor": 2},
        sharding_rules=rules_for("llama", "tp"), **common,
    )
    blk = tp.state.params["block0"]
    assert blk["attn"]["q"]["kernel"].sharding.spec == P(None, "tensor")
    assert blk["attn"]["k"]["kernel"].sharding.spec == P(None, "tensor")
    assert blk["gate"]["kernel"].sharding.spec == P(None, "tensor")
    assert blk["down"]["kernel"].sharding.spec == P("tensor", None)
    assert tp.state.params["lm_head"].sharding.spec == P(None, "tensor")
    tp.fit()
    np.testing.assert_allclose(dp.train_losses, tp.train_losses, rtol=1e-3)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_llama_sequence_parallel_matches_dp(tmp_path, impl):
    """llama's GQA repeats K/V to full heads before ops.attention, so
    BOTH sequence-parallel strategies compose with it unchanged:
    training on a {data:2, sequence:4} mesh matches the pure-DP
    trajectory (ulysses scatters the already-repeated heads — 4 heads /
    4-way axis)."""
    from ml_trainer_tpu.parallel import create_mesh

    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=1024, seed=2)
    common = dict(
        datasets=(ds, ds), epochs=1, batch_size=16, metric=None,
        optimizer="adamw", lr=0.01, seed=6, is_parallel=True, backend="cpu",
    )
    dp = Trainer(get_model("llama_tiny"),
                 model_dir=str(tmp_path / "dp"), **common)
    dp.fit()
    mesh = create_mesh({"data": 2, "sequence": 4})
    sp = Trainer(
        get_model("llama_tiny", attention_impl=impl, mesh=mesh),
        model_dir=str(tmp_path / "sp"),
        mesh_shape={"data": 2, "sequence": 4}, **common,
    )
    sp.fit()
    np.testing.assert_allclose(dp.train_losses, sp.train_losses, rtol=1e-3)


def test_llama_remat_matches_plain(tmp_path):
    ds = SyntheticTokens(size=16, seq_len=16, vocab_size=1024, seed=1)
    common = dict(
        datasets=(ds, ds), epochs=1, batch_size=8, metric=None,
        optimizer="adamw", lr=0.01, seed=4,
    )
    plain = Trainer(get_model("llama_tiny"),
                    model_dir=str(tmp_path / "p"), **common)
    plain.fit()
    remat = Trainer(get_model("llama_tiny", remat=True, remat_policy="dots"),
                    model_dir=str(tmp_path / "r"), **common)
    remat.fit()
    np.testing.assert_allclose(
        plain.train_losses, remat.train_losses, rtol=1e-5
    )
