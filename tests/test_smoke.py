"""Fast-lane training smoke: ONE tiny end-to-end fit + resume + test.

The `-m "not slow"` subset is the CI gate that must finish in minutes;
the trajectory-equality and multi-process proofs live in the slow lane.
This file keeps the fast lane honest about the core loop: a fit() that
trains, checkpoints, resumes, and serves test() must work before any
deeper property can.
"""

import numpy as np
import pytest

from ml_trainer_tpu import MLModel, Trainer
from ml_trainer_tpu.data import Loader, SyntheticCIFAR10


def test_fit_resume_and_test_smoke(tmp_path):
    ds = (SyntheticCIFAR10(size=32, seed=0), SyntheticCIFAR10(size=16, seed=1))
    common = dict(
        datasets=ds, batch_size=16, model_dir=str(tmp_path),
        metric="accuracy", optimizer="adam", lr=0.001,
    )
    t = Trainer(MLModel(), epochs=1, **common)
    t.fit()
    assert len(t.train_losses) == 1 and np.isfinite(t.train_losses[0])

    resumed = Trainer(MLModel(), epochs=2, **common)
    resumed.fit(resume=True)
    assert resumed.train_losses[0] == pytest.approx(t.train_losses[0])
    assert len(resumed.train_losses) == 2

    loss, acc = resumed.test(
        None, Loader(SyntheticCIFAR10(size=16, seed=2), batch_size=16)
    )
    assert np.isfinite(loss) and 0.0 <= acc <= 1.0
