// Native input-pipeline worker — the TPU-side replacement for torch
// DataLoader's C-backed worker pool (SURVEY.md §2B: "DataLoader worker
// pool ... batched, shuffled, sampler-driven host-side loading").
//
// A BatchWorker owns the dataset arrays (uint8 NHWC images + int32 labels,
// zero-copy views of the caller's numpy buffers) and a team of pthreads
// that assemble augmented batches into a bounded ring buffer ahead of the
// consumer: index-gather, random crop with zero padding, horizontal flip,
// uint8->float32 scale and per-channel normalize — the exact pipeline of
// the reference's transform (ref: src/utils/functions.py:5-12) — fused
// into one pass over the batch with no intermediate materialization.
// Randomness is a per-batch-seeded xorshift so results are reproducible
// regardless of thread scheduling.
//
// C ABI (ctypes-friendly); see ml_trainer_tpu/data/native.py for the
// Python side.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

// csrc/jpeg_decoder.cpp — the in-worker decode stage of the
// compressed-shard path.
extern "C" int jpeg_decode_expect(const uint8_t* buf, int64_t len,
                                  uint8_t* out, int64_t out_cap,
                                  int expect_w, int expect_h);

namespace {

struct Rng {  // xorshift64* — deterministic, cheap, per-batch seeded
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1Dull;
  }
  uint32_t below(uint32_t n) { return static_cast<uint32_t>(next() % n); }
  float uniform() { return (next() >> 40) * (1.0f / (1ull << 24)); }
};

struct Batch {
  int64_t id;
  std::vector<float> images;    // [B, H, W, C] transformed
  std::vector<int32_t> labels;  // [B]
};

struct Config {
  int height, width, channels;
  int pad;              // random-crop zero padding (0 = no crop)
  int flip;             // 1 = random horizontal flip
  int normalize;        // 1 = scale to [0,1] then (x - mean) / std
  float mean[8], std_[8];
};

class BatchWorker {
 public:
  // segs/seg_starts: the dataset's image storage as sorted segments —
  // one for an in-RAM array, many for memory-mapped on-disk shards
  // (ml_trainer_tpu/data/sharded.py).  The gather below gets its image
  // pointer via segment lookup, so worker threads read mapped pages
  // directly: the beyond-RAM streaming path IS the normal path.
  // seg_offs (optional, JPEG mode): per-segment [n_s + 1] byte offsets —
  // segment s's sample i occupies bytes [offs[i], offs[i+1]) of segs_[s],
  // holding one baseline-JPEG stream that worker threads DECODE before
  // the fused augmentation pass (compressed shards stay compressed on
  // disk AND in the page cache; only the in-flight batch is ever pixels).
  BatchWorker(std::vector<const uint8_t*> segs,
              std::vector<int64_t> seg_starts, const int32_t* labels,
              int64_t n, Config cfg, int batch, int threads, int queue_cap,
              uint64_t seed,
              std::vector<const int64_t*> seg_offs = {})
      : segs_(std::move(segs)), seg_starts_(std::move(seg_starts)),
        seg_offs_(std::move(seg_offs)), labels_(labels), n_(n), cfg_(cfg),
        batch_(batch), cap_(queue_cap), seed_(seed) {
    for (int t = 0; t < threads; ++t)
      team_.emplace_back([this] { Work(); });
  }

  int64_t DecodeErrors() const { return decode_errors_.load(); }

  ~BatchWorker() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    cv_ready_.notify_all();
    for (auto& th : team_) th.join();
  }

  // Schedule batches [0, count) of the given epoch; indices is the
  // epoch-level permutation (length >= count * batch).
  void StartEpoch(const int64_t* indices, int64_t count, uint64_t epoch) {
    std::lock_guard<std::mutex> lk(mu_);
    indices_.assign(indices, indices + count * batch_);
    next_produce_ = 0;
    next_consume_ = 0;
    total_ = count;
    epoch_salt_ = 0xa0761d6478bd642full * (epoch + 1);
    done_.clear();
    ++gen_;  // invalidates any in-flight batches of an abandoned epoch
    cv_work_.notify_all();
  }

  // Blocking pop of the next in-order batch; returns batch size or -1.
  int64_t Next(float* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    if (next_consume_ >= total_) return -1;
    int64_t want = next_consume_;
    cv_ready_.wait(lk, [&] { return done_.count(want) || stop_; });
    if (stop_) return -1;
    Batch b = std::move(done_[want]);
    done_.erase(want);
    ++next_consume_;
    cv_work_.notify_all();  // consumer advanced: backpressure window moved
    lk.unlock();
    std::memcpy(out_images, b.images.data(), b.images.size() * sizeof(float));
    std::memcpy(out_labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
    return static_cast<int64_t>(b.labels.size());
  }

 private:
  void Work() {
    std::vector<int64_t> idx;
    for (;;) {
      int64_t my, my_gen;
      uint64_t my_salt;
      {
        std::unique_lock<std::mutex> lk(mu_);
        // Backpressure: stay at most cap_ batches ahead of the consumer.
        cv_work_.wait(lk, [&] {
          return stop_ || (next_produce_ < total_ &&
                           next_produce_ < next_consume_ + cap_);
        });
        if (stop_) return;
        my = next_produce_++;
        my_gen = gen_;
        my_salt = epoch_salt_;
        idx.assign(indices_.begin() + my * batch_,
                   indices_.begin() + (my + 1) * batch_);
      }
      Batch b = Assemble(my, idx, my_salt);
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (my_gen == gen_) done_[my] = std::move(b);
      }
      cv_ready_.notify_all();
    }
  }

  Batch Assemble(int64_t batch_idx, const std::vector<int64_t>& idx,
                 uint64_t epoch_salt) {
    const int h = cfg_.height, w = cfg_.width, c = cfg_.channels;
    const int64_t spp = static_cast<int64_t>(h) * w * c;  // samples' pixels
    Batch b;
    b.id = batch_idx;
    b.images.resize(batch_ * spp);
    b.labels.resize(batch_);
    Rng rng(seed_ ^ epoch_salt ^ (0x51ed2701ull * (batch_idx + 1)));
    // JPEG mode: each thread reuses one decode scratch across samples.
    thread_local std::vector<uint8_t> decoded;
    for (int i = 0; i < batch_; ++i) {
      const int64_t src = idx[i];
      // Segment holding this sample: seg_starts_ is sorted, first > src.
      const size_t seg =
          std::upper_bound(seg_starts_.begin(), seg_starts_.end(), src) -
          seg_starts_.begin() - 1;
      const int64_t local = src - seg_starts_[seg];
      const uint8_t* img;
      if (!seg_offs_.empty()) {
        const int64_t* offs = seg_offs_[seg];
        decoded.resize(spp);
        const int rc = jpeg_decode_expect(
            segs_[seg] + offs[local], offs[local + 1] - offs[local],
            decoded.data(), spp, w, h);
        if (rc != 0) {
          // A corrupt sample zeroes out rather than poisoning the whole
          // epoch; the consumer checks DecodeErrors() and can fail loud.
          std::memset(decoded.data(), 0, spp);
          decode_errors_.fetch_add(1);
        }
        img = decoded.data();
      } else {
        img = segs_[seg] + local * spp;
      }
      b.labels[i] = labels_[src];
      float* dst = b.images.data() + i * spp;
      const int oy = cfg_.pad ? static_cast<int>(rng.below(2 * cfg_.pad + 1)) : 0;
      const int ox = cfg_.pad ? static_cast<int>(rng.below(2 * cfg_.pad + 1)) : 0;
      const bool flip = cfg_.flip && rng.uniform() < 0.5f;
      for (int y = 0; y < h; ++y) {
        // source row for this output row under pad-then-crop: may fall in
        // the zero padding
        const int sy = y + oy - cfg_.pad;
        for (int x = 0; x < w; ++x) {
          const int out_x = flip ? (w - 1 - x) : x;
          const int sx = x + ox - cfg_.pad;
          float* px = dst + (static_cast<int64_t>(y) * w + out_x) * c;
          if (sy < 0 || sy >= h || sx < 0 || sx >= w) {
            for (int ch = 0; ch < c; ++ch)
              px[ch] = cfg_.normalize
                           ? (0.0f - cfg_.mean[ch]) / cfg_.std_[ch]
                           : 0.0f;
          } else {
            const uint8_t* sp = img + (static_cast<int64_t>(sy) * w + sx) * c;
            for (int ch = 0; ch < c; ++ch) {
              float v = sp[ch];
              if (cfg_.normalize)
                v = (v / 255.0f - cfg_.mean[ch]) / cfg_.std_[ch];
              px[ch] = v;
            }
          }
        }
      }
    }
    return b;
  }

  std::vector<const uint8_t*> segs_;
  std::vector<int64_t> seg_starts_;
  std::vector<const int64_t*> seg_offs_;  // empty = raw pixels mode
  std::atomic<int64_t> decode_errors_{0};
  const int32_t* labels_;
  int64_t n_;
  Config cfg_;
  int batch_;
  int cap_;
  uint64_t seed_;

  std::mutex mu_;
  std::condition_variable cv_work_, cv_ready_;
  std::vector<std::thread> team_;
  std::vector<int64_t> indices_;
  int64_t next_produce_ = 0, next_consume_ = 0, total_ = 0, gen_ = 0;
  uint64_t epoch_salt_ = 0;
  std::map<int64_t, Batch> done_;
  bool stop_ = false;
};

}  // namespace

extern "C" {

static Config make_config(int height, int width, int channels, int pad,
                          int flip, int normalize, const float* mean,
                          const float* std_dev) {
  Config cfg{};
  cfg.height = height;
  cfg.width = width;
  cfg.channels = channels;
  cfg.pad = pad;
  cfg.flip = flip;
  cfg.normalize = normalize;
  for (int i = 0; i < channels && i < 8; ++i) {
    cfg.mean[i] = mean ? mean[i] : 0.0f;
    cfg.std_[i] = std_dev ? std_dev[i] : 1.0f;
  }
  return cfg;
}

// Images arrive as num_segs memory-mapped (or in-RAM) segments;
// seg_starts[i] is the first global sample index of segment i (sorted,
// seg_starts[0] == 0).  An in-RAM ArrayDataset is simply the one-segment
// case.  Labels stay one in-RAM array — at 4 bytes/sample they are never
// the residency problem.
void* batch_worker_create_sharded(const uint8_t** seg_ptrs,
                                  const int64_t* seg_starts,
                                  int64_t num_segs, const int32_t* labels,
                                  int64_t n, int height, int width,
                                  int channels, int pad, int flip,
                                  int normalize, const float* mean,
                                  const float* std_dev, int batch,
                                  int threads, int queue_cap,
                                  uint64_t seed) {
  return new BatchWorker(
      std::vector<const uint8_t*>(seg_ptrs, seg_ptrs + num_segs),
      std::vector<int64_t>(seg_starts, seg_starts + num_segs), labels, n,
      make_config(height, width, channels, pad, flip, normalize, mean,
                  std_dev),
      batch, threads, queue_cap, seed);
}

// JPEG-compressed shards: segments hold concatenated baseline-JPEG byte
// streams; seg_off_ptrs[s] is segment s's [n_s + 1] offset table.  The
// worker threads decode each sample before the fused augmentation —
// torch DataLoader's per-item JPEG decode, TPU-host edition.  Requires
// channels == 3 (the decoder emits RGB; grayscale JPEGs replicate).
void* batch_worker_create_jpeg(const uint8_t** seg_ptrs,
                               const int64_t** seg_off_ptrs,
                               const int64_t* seg_starts, int64_t num_segs,
                               const int32_t* labels, int64_t n, int height,
                               int width, int channels, int pad, int flip,
                               int normalize, const float* mean,
                               const float* std_dev, int batch, int threads,
                               int queue_cap, uint64_t seed) {
  if (channels != 3) return nullptr;
  return new BatchWorker(
      std::vector<const uint8_t*>(seg_ptrs, seg_ptrs + num_segs),
      std::vector<int64_t>(seg_starts, seg_starts + num_segs), labels, n,
      make_config(height, width, channels, pad, flip, normalize, mean,
                  std_dev),
      batch, threads, queue_cap, seed,
      std::vector<const int64_t*>(seg_off_ptrs, seg_off_ptrs + num_segs));
}

int64_t batch_worker_decode_errors(void* worker) {
  return static_cast<BatchWorker*>(worker)->DecodeErrors();
}

void batch_worker_start_epoch(void* worker, const int64_t* indices,
                              int64_t num_batches, uint64_t epoch) {
  static_cast<BatchWorker*>(worker)->StartEpoch(indices, num_batches, epoch);
}

int64_t batch_worker_next(void* worker, float* out_images,
                          int32_t* out_labels) {
  return static_cast<BatchWorker*>(worker)->Next(out_images, out_labels);
}

void batch_worker_destroy(void* worker) {
  delete static_cast<BatchWorker*>(worker);
}

}  // extern "C"
