// Minimal baseline JPEG decoder — the in-worker decode stage of the
// compressed-shard input pipeline (VERDICT r4 #5: torch DataLoader
// workers decode JPEG per item; this framework's workers previously
// could not, forcing raw uint8 shards at ~13x the source size on disk).
//
// Scope: baseline sequential DCT (SOF0/SOF1), 8-bit, 1 or 3 components,
// any sampling factors up to 4 (4:4:4 / 4:2:2 / 4:2:0 covered), restart
// markers, FF00 byte unstuffing.  Progressive (SOF2), arithmetic coding
// and 12-bit are rejected with a clean error — the shard INGEST encodes
// baseline (PIL default), so the decoder only ever sees what the writer
// produces.  Output is always interleaved RGB (grayscale replicates),
// matching the augmentation pass's NHWC uint8 input.
//
// Design notes: canonical Huffman decode bit-by-bit (mincode/maxcode/
// valptr), dequantize in zigzag order, separable float IDCT from a
// precomputed cosine basis (accurate: differences vs libjpeg come only
// from rounding), libjpeg-style triangular ("fancy") chroma upsampling
// for the 2x ratios (4:2:2 / 4:2:0), nearest-neighbor only as the
// generic fallback for other factors; ingest defaults to 4:4:4 where no
// upsampling happens at all.
// Implemented fresh from the public JPEG (ITU-T T.81) format.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint8_t kZigzag[64] = {
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

struct HuffTable {
  bool present = false;
  uint8_t counts[17] = {0};  // counts[l]: codes of bit-length l (1..16)
  int mincode[17], maxcode[17], valptr[17];
  std::vector<uint8_t> symbols;

  void Build() {
    int code = 0, k = 0;
    for (int l = 1; l <= 16; ++l) {
      valptr[l] = k;
      mincode[l] = code;
      maxcode[l] = counts[l] ? code + counts[l] - 1 : -1;
      code = (code + counts[l]) << 1;
      k += counts[l];
    }
    present = true;
  }
};

struct Component {
  int id = 0, h = 1, v = 1, tq = 0, td = 0, ta = 0;
  int dc_pred = 0;
  int plane_w = 0, plane_h = 0;  // padded to whole blocks across MCUs
  std::vector<uint8_t> plane;
};

// Entropy-coded-segment reader: FF00 unstuffing, stops (returning zero
// bits) at any real marker so corrupt streams terminate instead of
// running away.
struct BitReader {
  const uint8_t* p;
  const uint8_t* end;
  int bitpos = 0;
  bool at_marker = false;

  BitReader(const uint8_t* begin, const uint8_t* stop) : p(begin), end(stop) {}

  int GetBit() {
    if (at_marker || p >= end) return 0;
    const int bit = (*p >> (7 - bitpos)) & 1;
    if (++bitpos == 8) {
      bitpos = 0;
      if (*p == 0xFF) {
        if (p + 1 < end && p[1] == 0x00) {
          p += 2;  // stuffed data byte
        } else {
          at_marker = true;  // real marker: stop producing bits
        }
      } else {
        ++p;
      }
    }
    return bit;
  }

  int Receive(int n) {
    int v = 0;
    for (int i = 0; i < n; ++i) v = (v << 1) | GetBit();
    return v;
  }

  // Byte-align and consume an RSTn marker.  Returns false on anything
  // unexpected.
  bool SkipRestart(int n) {
    if (!at_marker && bitpos > 0) {
      // Discard the padding bits of the partially-consumed byte (the
      // encoder 1-pads the last entropy byte before a marker); the
      // advance must honor FF00 stuffing like GetBit does.
      if (*p == 0xFF) {
        if (p + 1 < end && p[1] == 0x00) p += 2;
      } else {
        ++p;
      }
    }
    bitpos = 0;
    at_marker = false;
    if (p + 1 < end && p[0] == 0xFF && p[1] == uint8_t(0xD0 + (n & 7))) {
      p += 2;
      return true;
    }
    return false;
  }
};

struct Decoder {
  const uint8_t* buf;
  int64_t len;
  int64_t pos = 0;

  int width = 0, height = 0, ncomp = 0;
  int hmax = 1, vmax = 1;
  int restart_interval = 0;
  uint16_t qtab[4][64] = {{0}};
  HuffTable dc[4], ac[4];
  Component comp[3];
  const char* error = nullptr;

  bool Fail(const char* msg) {
    if (!error) error = msg;
    return false;
  }

  int U8() { return pos < len ? buf[pos++] : -1; }
  int U16() {
    const int hi = U8(), lo = U8();
    return (hi < 0 || lo < 0) ? -1 : (hi << 8) | lo;
  }

  bool ParseHeaders() {
    if (U16() != 0xFFD8) return Fail("not a JPEG (no SOI)");
    for (;;) {
      int m = U8();
      while (m == 0xFF) m = U8();  // fill bytes before a marker code
      if (m < 0) return Fail("EOF before SOS");
      const int marker = 0xFF00 | m;
      if (marker == 0xFFD8) continue;  // stray SOI
      const int seglen = U16();
      if (seglen < 2 || pos + seglen - 2 > len)
        return Fail("bad segment length");
      const int64_t seg_end = pos + seglen - 2;
      switch (marker) {
        case 0xFFC0:
        case 0xFFC1:
          if (!ParseSOF(seg_end)) return false;
          break;
        case 0xFFC2:
          return Fail("progressive JPEG unsupported (ingest writes "
                      "baseline)");
        case 0xFFC4:
          if (!ParseDHT(seg_end)) return false;
          break;
        case 0xFFDB:
          if (!ParseDQT(seg_end)) return false;
          break;
        case 0xFFDD:
          if (seglen != 4) return Fail("bad DRI length");
          restart_interval = U16();
          break;
        case 0xFFDA:
          if (!ParseSOS(seg_end)) return false;
          return true;  // entropy data follows; pos is at its start
        default:
          if (marker >= 0xFFC5 && marker <= 0xFFC7)
            return Fail("unsupported SOF type");
          if (marker >= 0xFFC9 && marker <= 0xFFCB)
            return Fail("arithmetic coding unsupported");
          pos = seg_end;  // APPn / COM / others: skip
      }
      if (pos != seg_end) pos = seg_end;
    }
  }

  bool ParseSOF(int64_t seg_end) {
    const int prec = U8();
    if (prec != 8) return Fail("only 8-bit precision supported");
    height = U16();
    width = U16();
    ncomp = U8();
    if (height <= 0 || width <= 0) return Fail("bad dimensions");
    if (ncomp != 1 && ncomp != 3) return Fail("only 1 or 3 components");
    for (int i = 0; i < ncomp; ++i) {
      comp[i].id = U8();
      const int hv = U8();
      comp[i].h = hv >> 4;
      comp[i].v = hv & 15;
      comp[i].tq = U8();
      if (comp[i].h < 1 || comp[i].h > 4 || comp[i].v < 1 || comp[i].v > 4)
        return Fail("bad sampling factors");
      if (comp[i].tq > 3) return Fail("bad quant table id");
      hmax = std::max(hmax, comp[i].h);
      vmax = std::max(vmax, comp[i].v);
    }
    return pos <= seg_end || Fail("SOF overruns segment");
  }

  bool ParseDQT(int64_t seg_end) {
    while (pos < seg_end) {
      const int pq_tq = U8();
      const int pq = pq_tq >> 4, tq = pq_tq & 15;
      if (tq > 3) return Fail("bad DQT id");
      if (pq != 0) return Fail("16-bit quant tables unsupported");
      for (int i = 0; i < 64; ++i) qtab[tq][i] = uint16_t(U8());
    }
    return true;
  }

  bool ParseDHT(int64_t seg_end) {
    while (pos < seg_end) {
      const int tc_th = U8();
      const int tc = tc_th >> 4, th = tc_th & 15;
      if (tc > 1 || th > 3) return Fail("bad DHT id");
      HuffTable& t = tc ? ac[th] : dc[th];
      t.symbols.clear();
      int total = 0;
      for (int l = 1; l <= 16; ++l) {
        t.counts[l] = uint8_t(U8());
        total += t.counts[l];
      }
      if (total > 256) return Fail("bad DHT counts");
      t.symbols.resize(total);
      for (int i = 0; i < total; ++i) t.symbols[i] = uint8_t(U8());
      t.Build();
    }
    return true;
  }

  bool ParseSOS(int64_t seg_end) {
    const int ns = U8();
    if (ns != ncomp) return Fail("non-interleaved scans unsupported");
    for (int i = 0; i < ns; ++i) {
      const int cs = U8(), tdta = U8();
      Component* c = nullptr;
      for (int k = 0; k < ncomp; ++k)
        if (comp[k].id == cs) c = &comp[k];
      if (!c) return Fail("SOS names unknown component");
      c->td = tdta >> 4;
      c->ta = tdta & 15;
      if (!dc[c->td].present || !ac[c->ta].present)
        return Fail("SOS references missing Huffman table");
    }
    U8();  // Ss
    U8();  // Se
    U8();  // Ah/Al
    return pos <= seg_end || Fail("SOS overruns segment");
  }

  static int DecodeHuffSymbol(BitReader& br, const HuffTable& t) {
    int code = 0;
    for (int l = 1; l <= 16; ++l) {
      code = (code << 1) | br.GetBit();
      if (t.counts[l] && code <= t.maxcode[l])
        return t.symbols[t.valptr[l] + code - t.mincode[l]];
    }
    return -1;
  }

  static int Extend(int v, int s) {
    return (s && v < (1 << (s - 1))) ? v - (1 << s) + 1 : v;
  }

  // Separable float IDCT from the precomputed cosine basis: accurate to
  // rounding, which is what the parity tests need.
  static const float* CosBasis() {
    static float basis[8][8];
    static bool init = false;
    if (!init) {
      for (int u = 0; u < 8; ++u) {
        const float cu = u == 0 ? float(1.0 / std::sqrt(2.0)) : 1.0f;
        for (int x = 0; x < 8; ++x)
          basis[u][x] = 0.5f * cu *
                        std::cos(float((2 * x + 1) * u) * float(M_PI) / 16.0f);
      }
      init = true;
    }
    return &basis[0][0];
  }

  static void Idct8x8(const float in[64], uint8_t out[64]) {
    const float* basis = CosBasis();  // basis[u*8 + x]
    float tmp[64];
    for (int y = 0; y < 8; ++y) {  // rows: sum over u
      for (int x = 0; x < 8; ++x) {
        float s = 0;
        for (int u = 0; u < 8; ++u) s += basis[u * 8 + x] * in[y * 8 + u];
        tmp[y * 8 + x] = s;
      }
    }
    for (int x = 0; x < 8; ++x) {  // cols: sum over v
      for (int y = 0; y < 8; ++y) {
        float s = 0;
        for (int v = 0; v < 8; ++v) s += basis[v * 8 + y] * tmp[v * 8 + x];
        const int px = int(std::lround(s)) + 128;
        out[y * 8 + x] = uint8_t(px < 0 ? 0 : px > 255 ? 255 : px);
      }
    }
  }

  bool DecodeBlock(BitReader& br, Component& c, uint8_t* dst, int stride) {
    float block[64] = {0};
    const uint16_t* q = qtab[c.tq];
    const int t = DecodeHuffSymbol(br, dc[c.td]);
    if (t < 0) return Fail("bad DC Huffman code");
    const int diff = Extend(br.Receive(t), t);
    c.dc_pred += diff;
    block[0] = float(c.dc_pred) * float(q[0]);
    for (int k = 1; k < 64;) {
      const int rs = DecodeHuffSymbol(br, ac[c.ta]);
      if (rs < 0) return Fail("bad AC Huffman code");
      const int r = rs >> 4, s = rs & 15;
      if (s == 0) {
        if (r == 15) {
          k += 16;  // ZRL
          continue;
        }
        break;  // EOB
      }
      k += r;
      if (k > 63) return Fail("AC run past block end");
      block[kZigzag[k]] = float(Extend(br.Receive(s), s)) * float(q[k]);
      ++k;
    }
    uint8_t px[64];
    Idct8x8(block, px);
    for (int y = 0; y < 8; ++y)
      std::memcpy(dst + y * stride, px + y * 8, 8);
    return true;
  }

  bool DecodeScan() {
    const int mcux = (width + 8 * hmax - 1) / (8 * hmax);
    const int mcuy = (height + 8 * vmax - 1) / (8 * vmax);
    for (int i = 0; i < ncomp; ++i) {
      comp[i].plane_w = mcux * comp[i].h * 8;
      comp[i].plane_h = mcuy * comp[i].v * 8;
      comp[i].plane.assign(size_t(comp[i].plane_w) * comp[i].plane_h, 0);
      comp[i].dc_pred = 0;
    }
    BitReader br(buf + pos, buf + len);
    int rst = 0, until_restart = restart_interval;
    for (int my = 0; my < mcuy; ++my) {
      for (int mx = 0; mx < mcux; ++mx) {
        if (restart_interval && until_restart == 0) {
          if (!br.SkipRestart(rst)) return Fail("missing restart marker");
          rst = (rst + 1) & 7;
          for (int i = 0; i < ncomp; ++i) comp[i].dc_pred = 0;
          until_restart = restart_interval;
        }
        for (int i = 0; i < ncomp; ++i) {
          Component& c = comp[i];
          for (int by = 0; by < c.v; ++by) {
            for (int bx = 0; bx < c.h; ++bx) {
              uint8_t* dst = c.plane.data() +
                             size_t(my * c.v + by) * 8 * c.plane_w +
                             size_t(mx * c.h + bx) * 8;
              if (!DecodeBlock(br, c, dst, c.plane_w)) return false;
            }
          }
        }
        if (restart_interval) --until_restart;
      }
    }
    return true;
  }

  // Upsample one component to full [height, width] resolution.  Exact
  // 2x ratios use the triangular (weights 3/4, 1/4) filter with the
  // rounding offsets decoders standardized on, so 4:2:0 / 4:2:2 output
  // matches libjpeg's default "fancy" upsampling; other ratios fall
  // back to nearest-neighbor replication.
  void Upsample(const Component& c, std::vector<uint8_t>& out) const {
    const int rh = hmax / c.h, rv = vmax / c.v;
    const int cw = (width * c.h + hmax - 1) / hmax;
    const int ch = (height * c.v + vmax - 1) / vmax;
    out.resize(size_t(width) * height);
    const uint8_t* plane = c.plane.data();
    const int stride = c.plane_w;
    auto in = [&](int r, int x) -> int {
      return plane[size_t(r < 0 ? 0 : r >= ch ? ch - 1 : r) * stride +
                   (x < 0 ? 0 : x >= cw ? cw - 1 : x)];
    };
    if (rh == 1 && rv == 1) {
      for (int r = 0; r < height; ++r)
        std::memcpy(out.data() + size_t(r) * width,
                    plane + size_t(r) * stride, width);
      return;
    }
    if (rh == 2 && rv == 1) {  // h2v1 triangular per row
      for (int r = 0; r < height; ++r) {
        uint8_t* o = out.data() + size_t(r) * width;
        for (int x = 0; x < cw; ++x) {
          const int v = in(r, x) * 3;
          const int even = x == 0 ? in(r, 0) : (v + in(r, x - 1) + 1) >> 2;
          const int odd =
              x == cw - 1 ? in(r, cw - 1) : (v + in(r, x + 1) + 2) >> 2;
          if (2 * x < width) o[2 * x] = uint8_t(even);
          if (2 * x + 1 < width) o[2 * x + 1] = uint8_t(odd);
        }
      }
      return;
    }
    if (rh == 2 && rv == 2) {  // h2v2 triangular in both dimensions
      for (int orow = 0; orow < height; ++orow) {
        const int ir = orow >> 1;
        const int near = (orow & 1) ? ir + 1 : ir - 1;
        uint8_t* o = out.data() + size_t(orow) * width;
        // colsum[x] = 3*cur + near, then the same 3:1 filter across x
        // with the canonical rounding offsets (8 even, 7 odd).
        auto colsum = [&](int x) { return in(ir, x) * 3 + in(near, x); };
        for (int x = 0; x < cw; ++x) {
          const int cs = colsum(x) * 3;
          const int even = x == 0 ? (colsum(0) * 4 + 8) >> 4
                                  : (cs + colsum(x - 1) + 8) >> 4;
          const int odd = x == cw - 1 ? (colsum(cw - 1) * 4 + 7) >> 4
                                      : (cs + colsum(x + 1) + 7) >> 4;
          if (2 * x < width) o[2 * x] = uint8_t(even);
          if (2 * x + 1 < width) o[2 * x + 1] = uint8_t(odd);
        }
      }
      return;
    }
    for (int r = 0; r < height; ++r) {  // generic nearest
      uint8_t* o = out.data() + size_t(r) * width;
      const uint8_t* row = plane + size_t(r * c.v / vmax) * stride;
      for (int x = 0; x < width; ++x) o[x] = row[x * c.h / hmax];
    }
  }

  // Interleaved RGB out (grayscale replicated).
  void EmitRGB(uint8_t* out) const {
    if (ncomp == 1) {
      const Component& y = comp[0];
      for (int r = 0; r < height; ++r)
        for (int cidx = 0; cidx < width; ++cidx) {
          const uint8_t v = y.plane[size_t(r) * y.plane_w + cidx];
          uint8_t* px = out + (size_t(r) * width + cidx) * 3;
          px[0] = px[1] = px[2] = v;
        }
      return;
    }
    std::vector<uint8_t> yb, bb, rb;
    Upsample(comp[0], yb);
    Upsample(comp[1], bb);
    Upsample(comp[2], rb);
    for (size_t i = 0, n = size_t(width) * height; i < n; ++i) {
      const float Y = float(yb[i]);
      const float Cb = float(bb[i]) - 128.0f;
      const float Cr = float(rb[i]) - 128.0f;
      const int R = int(std::lround(Y + 1.402f * Cr));
      const int G = int(std::lround(Y - 0.344136f * Cb - 0.714136f * Cr));
      const int B = int(std::lround(Y + 1.772f * Cb));
      out[i * 3 + 0] = uint8_t(R < 0 ? 0 : R > 255 ? 255 : R);
      out[i * 3 + 1] = uint8_t(G < 0 ? 0 : G > 255 ? 255 : G);
      out[i * 3 + 2] = uint8_t(B < 0 ? 0 : B > 255 ? 255 : B);
    }
  }
};

}  // namespace

extern "C" {

// Peek dimensions without decoding.  Returns 0 on success.
int jpeg_decode_info(const uint8_t* buf, int64_t len, int* w, int* h,
                     int* c) {
  Decoder d{buf, len};
  if (!d.ParseHeaders()) return -1;
  *w = d.width;
  *h = d.height;
  *c = d.ncomp;
  return 0;
}

// Decode to interleaved RGB uint8 [h, w, 3].  out_cap guards the output
// buffer.  Returns 0 on success, negative on error.
int jpeg_decode(const uint8_t* buf, int64_t len, uint8_t* out,
                int64_t out_cap) {
  Decoder d{buf, len};
  if (!d.ParseHeaders()) return -1;
  if (int64_t(d.width) * d.height * 3 > out_cap) return -2;
  if (!d.DecodeScan()) return -3;
  d.EmitRGB(out);
  return 0;
}

// As jpeg_decode, but rejects images whose dimensions differ from the
// expectation (-4) — the batch worker's samples are all one shape, and
// a mismatched image must fail rather than write a misshaped buffer.
int jpeg_decode_expect(const uint8_t* buf, int64_t len, uint8_t* out,
                       int64_t out_cap, int expect_w, int expect_h) {
  Decoder d{buf, len};
  if (!d.ParseHeaders()) return -1;
  if (d.width != expect_w || d.height != expect_h) return -4;
  if (int64_t(d.width) * d.height * 3 > out_cap) return -2;
  if (!d.DecodeScan()) return -3;
  d.EmitRGB(out);
  return 0;
}

}  // extern "C"
