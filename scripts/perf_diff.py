#!/usr/bin/env python
"""Perf-regression attribution: diff two perf artifacts and rank what
changed, attributed through the existing ledgers.

Any two JSON artifacts the repo emits are diffable — a ``bench.py``
artifact (``docs/*_cpu.json``), a ``run_report.json``, a Watchtower
TSDB dump (``TimeSeriesStore.save()``), or a fastlane timings file —
because everything reduces to numeric leaves under dotted keys.  The
output is a ranked "what changed" table, each row attributed to the
ledger family its key belongs to (goodput buckets, comm bytes, compile
counts, step-ms percentiles, kv/adapter pool pressure, ...), so a
ratchet failure in ``bench_gate.py`` prints WHERE the regression lives
rather than just that one scalar moved::

    python scripts/perf_diff.py docs/serving_cpu.json /tmp/serving_now.json
    python scripts/perf_diff.py old_report.json new_report.json --top 15

``record`` is the fastlane timing helper (one call per leg in
``scripts/fastlane.sh``; the resulting ``docs/fastlane_timings.json``
files are themselves diffable)::

    python scripts/perf_diff.py record --file docs/fastlane_timings.json \
        --leg serving --seconds 41.2

Stdlib-only, host-only — importable from ``bench_gate.py`` without
touching jax.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

# Attribution: first matching pattern names the ledger family a key
# belongs to.  Order matters — e.g. `compile` outranks the `_ms` latency
# catch-all so `compile_ms` lands in compiles.
CATEGORIES: Tuple[Tuple[str, str], ...] = (
    ("goodput", r"goodput|wall_clock|productive|overhead_fraction"),
    ("compiles", r"compil"),
    ("comm", r"comm_|_bytes|bandwidth|allreduce|allgather|reduce_scatter"),
    ("kv/pools", r"kv_|pages|adapter|pool|evict|spill"),
    ("slo/alerts", r"slo|burn|attainment|alert"),
    ("latency", r"ttft|tpot|e2e|queue_wait|_ms\b|_ms[._]|latency|p50|p9\d"),
    ("throughput", r"per_sec|per_token|throughput|mfu|samples|tokens"),
    ("resilience", r"straggler|desync|rollback|preempt|reshape|skipped"),
    ("timings", r"seconds|elapsed|duration|_s\b"),
)

# Keys that are wall-time stamps or identifiers, not perf signals.
_IGNORE_RE = re.compile(
    r"(^|\.)(written_at|measured|recorded_at|rotated_at|ts|t|time"
    r"|unixtime|version|seed|pid|port)($|\.)"
)


def categorize(key: str) -> str:
    low = key.lower()
    for name, pat in CATEGORIES:
        if re.search(pat, low):
            return name
    return "other"


def flatten(obj, prefix: str = "", out: Optional[Dict[str, float]] = None,
            ) -> Dict[str, float]:
    """Numeric leaves of any nested JSON value under dotted keys.  Lists
    of dicts index by a `name`/`model`/`leg`-like field when one exists
    (stable across runs) and by position otherwise."""
    if out is None:
        out = {}
    if isinstance(obj, bool):
        out[prefix] = float(obj)
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            flatten(v, f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            tag = str(i)
            if isinstance(v, dict):
                for id_key in ("name", "model", "leg", "fn", "rule"):
                    if isinstance(v.get(id_key), str):
                        tag = v[id_key]
                        break
            flatten(v, f"{prefix}[{tag}]" if prefix else f"[{tag}]", out)
    return out


def _is_tsdb_dump(payload) -> bool:
    return (
        isinstance(payload, dict)
        and isinstance(payload.get("series"), list)
        and all(
            isinstance(s, dict) and "points" in s and "name" in s
            for s in payload["series"]
        )
    )


def _flatten_tsdb(payload: dict) -> Dict[str, float]:
    """A Watchtower dump reduces to one leaf per series — its LAST
    sample (the state the run ended in) — keyed by the exposition-style
    series key, so two dumps diff like two scrapes."""
    out: Dict[str, float] = {}
    for s in payload["series"]:
        labels = s.get("labels") or {}
        key = s["name"]
        if labels:
            inner = ",".join(
                f"{k}={v}" for k, v in sorted(labels.items())
            )
            key = f"{s['name']}{{{inner}}}"
        pts = s.get("points") or []
        if pts:
            out[key] = float(pts[-1][1])
    return out


def load_leaves(path: str) -> Dict[str, float]:
    with open(path, encoding="utf-8") as fp:
        payload = json.load(fp)
    if _is_tsdb_dump(payload):
        return _flatten_tsdb(payload)
    return flatten(payload)


def diff_leaves(old: Dict[str, float], new: Dict[str, float],
                min_pct: float = 0.5) -> List[dict]:
    """Ranked change rows: every key present in both sides whose value
    moved at least ``min_pct`` percent (or appeared/vanished), sorted by
    relative magnitude — the "what changed" table."""
    rows: List[dict] = []
    for key in sorted(set(old) | set(new)):
        if _IGNORE_RE.search(key):
            continue
        a, b = old.get(key), new.get(key)
        if a is None or b is None:
            rows.append({
                "key": key, "category": categorize(key),
                "old": a, "new": b, "delta": None,
                "pct": float("inf"),
                "note": "appeared" if a is None else "vanished",
            })
            continue
        if a == b:
            continue
        delta = b - a
        pct = abs(delta) / abs(a) * 100.0 if a else float("inf")
        if pct < min_pct:
            continue
        rows.append({
            "key": key, "category": categorize(key),
            "old": a, "new": b, "delta": delta, "pct": pct, "note": "",
        })
    rows.sort(key=lambda r: (-r["pct"], r["key"]))
    return rows


def diff_files(old_path: str, new_path: str,
               min_pct: float = 0.5) -> List[dict]:
    return diff_leaves(
        load_leaves(old_path), load_leaves(new_path), min_pct=min_pct
    )


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v != v:  # NaN
        return "nan"
    if abs(v) >= 1e6 or (v and abs(v) < 1e-3):
        return f"{v:.3g}"
    return f"{v:.4g}"


def format_table(rows: List[dict], top: int = 20) -> str:
    """The ranked attribution table plus a per-ledger rollup — what
    ``bench_gate.py`` prints under a failed ratchet."""
    if not rows:
        return "no numeric leaves changed"
    shown = rows[:top]
    headers = ("category", "key", "old", "new", "delta", "pct")
    table = [
        (
            r["category"], r["key"], _fmt(r["old"]), _fmt(r["new"]),
            _fmt(r["delta"]) if r["delta"] is not None else r["note"],
            "new" if r["pct"] == float("inf") else f"{r['pct']:+.1f}%"
            if r["delta"] is not None and r["delta"] > 0
            else ("" if r["pct"] == float("inf") else f"-{r['pct']:.1f}%"),
        )
        for r in shown
    ]
    widths = [
        max(len(headers[i]), *(len(t[i]) for t in table))
        for i in range(len(headers))
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += [
        "  ".join(c.ljust(w) for c, w in zip(t, widths)) for t in table
    ]
    by_cat: Dict[str, int] = {}
    for r in rows:
        by_cat[r["category"]] = by_cat.get(r["category"], 0) + 1
    rollup = ", ".join(
        f"{c}: {n}" for c, n in
        sorted(by_cat.items(), key=lambda kv: -kv[1])
    )
    lines.append("")
    lines.append(
        f"{len(rows)} changed leaves ({rollup})"
        + (f"; top {top} shown" if len(rows) > top else "")
    )
    return "\n".join(lines)


# -- fastlane timing recorder ---------------------------------------------


def record_timing(path: str, leg: str, seconds: float,
                  rc: Optional[int] = None) -> dict:
    """Upsert one leg's wall-clock into a timings file (atomic; the file
    itself is a diffable artifact: ``perf_diff.py old new`` attributes
    fastlane slowdowns per leg)."""
    try:
        with open(path, encoding="utf-8") as fp:
            payload = json.load(fp)
    except (OSError, json.JSONDecodeError):
        payload = {"version": 1, "legs": {}}
    entry = {"seconds": round(float(seconds), 3),
             "recorded_at": round(time.time(), 3)}
    if rc is not None:
        entry["rc"] = int(rc)
    payload.setdefault("legs", {})[leg] = entry
    payload["total_seconds"] = round(
        sum(v.get("seconds", 0.0) for v in payload["legs"].values()), 3
    )
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(payload, fp, indent=1, sort_keys=True)
        fp.write("\n")
    os.replace(tmp, path)
    return payload


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "record":
        ap = argparse.ArgumentParser(
            prog="perf_diff.py record",
            description="record one fastlane leg's wall-clock",
        )
        ap.add_argument("--file", required=True)
        ap.add_argument("--leg", required=True)
        ap.add_argument("--seconds", type=float, required=True)
        ap.add_argument("--rc", type=int, default=None)
        args = ap.parse_args(argv[1:])
        payload = record_timing(
            args.file, args.leg, args.seconds, rc=args.rc
        )
        print(
            f"recorded {args.leg}={args.seconds:.1f}s "
            f"(total {payload['total_seconds']:.1f}s) -> {args.file}"
        )
        return 0
    ap = argparse.ArgumentParser(
        description="diff two perf artifacts and attribute what changed",
    )
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--min-pct", type=float, default=0.5,
                    help="hide leaves that moved less than this percent")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw rows as JSON instead of the table")
    args = ap.parse_args(argv)
    rows = diff_files(args.old, args.new, min_pct=args.min_pct)
    if args.json:
        print(json.dumps(rows, indent=1, default=str))
    else:
        print(f"perf diff: {args.old} -> {args.new}")
        print(format_table(rows, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
