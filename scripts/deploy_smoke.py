#!/usr/bin/env python
"""Live-rollout smoke leg (scripts/fastlane.sh) — the train -> export
-> deploy loop end to end on a REAL multi-process fleet
(serving/deploy.py, docs/serving.md "Deploys"):

1. Fit a tiny gpt2 for one epoch (Trainer + SyntheticTokens) and
   export it — manifest + weights fingerprint included.
2. Spin a 2-process fleet on the seed init, put open-loop traffic on
   it, and ``Router.deploy`` the export MID-LOAD: new-generation
   worker processes spawn from the checkpoint (shared on-disk compile
   cache), warm off-path, take the canary slice, ramp to 100% and
   retire the old workers.  The client must see ZERO errors (no
   dropped streams), the old steady fleet's per-process compile counts
   must not move, and the promoted fleet must serve the TRAINED
   weights byte-identical to in-driver ``generate()``.
3. Deploy the same export again through a wedged factory (canary-only
   TTFT regression): the SLO-burn watch must roll back within one
   burn window, restore the pre-deploy replica set, and the stable
   slice's outputs must stay byte-identical throughout.

Prints ``DEPLOY_SMOKE OK`` / ``DEPLOY_SMOKE FAIL: <why>``; non-zero
exit on any violation.  CPU-only, tiny model, ~4 worker processes at
peak.
"""

import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"DEPLOY_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.checkpoint import (
        load_model_manifest, load_model_variables,
    )
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import DeployConfig, SloPolicy
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )

    work_dir = tempfile.mkdtemp(prefix="deploy_smoke_")
    ckpt_dir = os.path.join(work_dir, "export")

    # -- leg 1: train + export (the rollout target) -------------------
    model = get_model("gpt2_tiny", max_len=64)
    ds = SyntheticTokens(size=32, seq_len=16,
                         vocab_size=model.vocab_size, seed=0)
    Trainer(model, datasets=(ds, ds), epochs=1, batch_size=8,
            metric=None, model_dir=ckpt_dir, seed=7, lr=0.01).fit()
    manifest = load_model_manifest(ckpt_dir) or {}
    fp = manifest.get("weights_fingerprint")
    if not (fp and fp.startswith("w:")):
        return fail(f"export manifest missing weights fingerprint: "
                    f"{manifest}")
    trained = load_model_variables(ckpt_dir)
    seed_vars = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    print(f"# deploy smoke: trained + exported gpt2_tiny ({fp})")

    rng = np.random.default_rng(0)
    fleet = Fleet(
        roles=["both", "both"], model_name="gpt2_tiny", max_len=64,
        max_batch=4, max_queue=64, kv_page_size=8, seed=0,
        prefix_cache=False,
    )
    fleet.start()
    router = fleet.make_router(
        slo=SloPolicy(ttft_ms=2000.0, tpot_ms=2000.0, target=0.9),
        slo_timelines=256, hedging=False,
    )
    try:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"
        # 2 canary tenants + 6 stable ones (slice at 0.25).
        tenants = (
            [t for t in (f"t{i}" for i in range(64))
             if router.tenant_slice(t) < 0.25][:2]
            + [t for t in (f"t{i}" for i in range(64))
               if router.tenant_slice(t) >= 0.25][:6]
        )
        rows = [
            ScheduledRequest(
                arrival_s=float(i * 0.12),
                tenant=tenants[i % len(tenants)],
                prompt=rng.integers(
                    0, model.vocab_size, int(rng.integers(8, 17))
                ).astype(np.int32),
                max_new_tokens=8,
            )
            for i in range(16)
        ]
        trace = schedule_from_trace(schedule_to_records(rows))
        refs_seed = [
            [int(t) for t in np.asarray(
                generate(model, seed_vars, s.prompt[None],
                         s.max_new_tokens))[0]]
            for s in trace
        ]
        refs_trained = [
            [int(t) for t in np.asarray(
                generate(model, trained, s.prompt[None],
                         s.max_new_tokens))[0]]
            for s in trace
        ]
        for _ in range(2):  # untimed: workers compile to steady state
            run_open_loop(trace, url=url, time_scale=0.0)

        def worker_compiles():
            out = {}
            for rep in list(router.replicas.values()):
                try:
                    out[rep.name] = int(
                        rep.server._get("/v1/spec")["compiles"] or 0
                    )
                except Exception:
                    pass
            return out

        class Load:
            def __init__(self):
                self.passes = []
                self.stop = threading.Event()
                self.thread = threading.Thread(
                    target=self._run, daemon=True)
                self.thread.start()

            def _run(self):
                while not self.stop.is_set():
                    self.passes.append(run_open_loop(
                        trace, url=url, collect_tokens=True))

            def finish(self):
                self.stop.set()
                self.thread.join(timeout=600.0)
                return (
                    sum(p["n_errors"] for p in self.passes),
                    [r for p in self.passes for r in zip(
                        p["per_request"],
                        range(len(p["per_request"])))],
                )

        cfg = DeployConfig(
            canary=0.25, stages=(1.0,), hold_s=1.0,
            burn_threshold=2.0, high_polls=2, window_s=10.0,
            min_window_requests=2, stage_min_requests=2,
            poll_interval_s=0.3, drain_timeout_s=60.0,
        )

        # -- leg 2: healthy mid-load deploy ---------------------------
        steady_base = worker_compiles()
        load = Load()
        dep = router.deploy(ckpt_dir, canary=0.25, config=cfg)
        state = dep.wait(timeout=600.0)
        steady_after = {
            n: c for n, c in worker_compiles().items()
            if n in steady_base
        }
        n_errors, outs = load.finish()
        dep.close()
        if state != "done":
            return fail(f"healthy deploy ended '{state}', not done "
                        f"(cause: {dep.rollback_cause})")
        if dep.weights_fp != fp:
            return fail(f"served fingerprint {dep.weights_fp} != "
                        f"export manifest {fp}")
        if n_errors:
            return fail(f"{n_errors} client error(s) (dropped streams) "
                        "during the healthy deploy")
        for r, i in outs:
            if r.get("output") not in (refs_seed[i], refs_trained[i]):
                return fail(f"mid-deploy output {i} matches neither "
                            "generation's generate()")
        # The steady fleet's compiles must not move while the deploy
        # runs (old workers that retired cleanly drop out of the
        # post-sample; every one still answering must be unchanged).
        moved = {n: steady_after[n] - steady_base[n]
                 for n in steady_after
                 if steady_after[n] != steady_base[n]}
        if moved:
            return fail(f"steady-fleet compiles moved mid-deploy: "
                        f"{moved}")
        out = [int(t) for t in np.asarray(
            router.complete(trace[0].prompt, 8, timeout=300))]
        if out != refs_trained[0]:
            return fail("promoted fleet output != generate() on the "
                        "trained export")
        print(f"# deploy smoke: mid-load deploy done in "
              f"{dep.report()['elapsed_s']}s, {len(load.passes)} client "
              f"pass(es), 0 errors, promoted fleet byte-identical")

        # -- leg 3: forced regression -> auto-rollback ----------------
        base_factory = fleet.deploy_factory(ckpt_dir)

        def wedged_factory(role):
            remote = base_factory(role)
            orig = remote.submit_request

            def slow_submit(req):
                time.sleep(3.0)
                return orig(req)

            remote.submit_request = slow_submit
            return remote

        pre_replicas = sorted(router.replicas)
        load = Load()
        dep = router.deploy(ckpt_dir, canary=0.25,
                            factory=wedged_factory, config=cfg)
        state = dep.wait(timeout=600.0)
        n_errors, outs = load.finish()
        dep.close()
        if state != "rolled_back":
            return fail(f"forced regression ended '{state}', not "
                        "rolled_back")
        if "canary burn" not in (dep.rollback_cause or ""):
            return fail(f"rollback cause not burn-driven: "
                        f"{dep.rollback_cause}")
        if n_errors:
            return fail(f"{n_errors} client error(s) (dropped streams) "
                        "during the rollback")
        for r, i in outs:
            if r.get("output") != refs_trained[i]:
                return fail(f"output {i} diverged during the rollback "
                            "(gen2 shares gen1 weights; all outputs "
                            "must match)")
        if sorted(router.replicas) != pre_replicas:
            return fail(f"rollback did not restore the replica set: "
                        f"{sorted(router.replicas)} != {pre_replicas}")
        events = dep.report()["events"]
        first_burn = next(
            (e["t"] for e in events if e["action"] == "burn_high"),
            None,
        )
        rolled = next(
            (e["t"] for e in events if e["action"] == "transition"
             and e.get("to") == "rolled_back"), None,
        )
        if first_burn is None or rolled is None:
            return fail("rollback left no burn_high/rolled_back events")
        if rolled - first_burn > cfg.window_s:
            return fail(f"rollback took {rolled - first_burn:.1f}s — "
                        f"outside the {cfg.window_s}s burn window")
        print(f"# deploy smoke: forced regression rolled back "
              f"{rolled - first_burn:.1f}s after first high burn, "
              f"0 errors, fleet restored")
    finally:
        try:
            router.close()
        finally:
            fleet.stop()
            shutil.rmtree(work_dir, ignore_errors=True)
    print("DEPLOY_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
