#!/usr/bin/env python
"""Fastlane smoke: bucketed-overlap sharded update + bf16 mixed precision.

A 2-virtual-device pure-DP dryrun through the REAL Trainer step —
``dp_update='sharded'`` (bucketed reduce-scatter backward, 1/N weight
update, bucketed all-gather) composed with ``precision='bf16'`` and
dynamic loss scaling — asserting the invariants the tentpole promises:

* finite loss every epoch (the policy + scaling never poison a healthy
  run);
* ZERO recompiles across ragged step counts (one compiled program after
  two epochs of traffic, including an injected non-finite step — the
  guard/backoff is where-selected, not branched);
* an overflow halves the scale WITHOUT advancing the rollback streak;
* per-bucket reduce-scatter/all-gather bytes landed in the registry
  (``comm_bucket_bytes_total{op=,bucket=}``) and the overlap-fraction
  gauge is live;
* the fp32 fused path on the same data still matches its own trajectory
  shape (finite, decreasing-ish) — the smoke's sanity anchor.

Runs on CPU in seconds; exits non-zero on any violation.
"""

import os
import sys
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_bucket_bytes,
        reset_comm_stats,
    )
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.telemetry.registry import default_registry

    assert jax.device_count() >= 2, "2-virtual-device mesh not active"
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    workdir = tempfile.mkdtemp(prefix="mixed_smoke_")
    reset_comm_stats()

    trainer = Trainer(
        get_model("gpt2_tiny", vocab_size=256),
        datasets=(ds, ds), epochs=2, batch_size=8,
        model_dir=os.path.join(workdir, "bf16"),
        mesh_shape={"data": 2}, optimizer="adamw", metric=None, lr=1e-3,
        precision="bf16", dp_update="sharded", bucket_mb=0.25,
        telemetry=True, log_every_steps=2,
    )
    plan = trainer._bucket_plan
    assert plan is not None and len(plan.buckets) > 1, plan
    s0 = float(trainer.state.loss_scale)
    trainer.fit()
    assert all(np.isfinite(trainer.train_losses)), trainer.train_losses
    # The real recompile instrument (telemetry/compile_watch.py): the
    # sharded bf16 step compiled exactly once and NOTHING compiled after
    # the first epoch declared warmup done.
    from ml_trainer_tpu.telemetry import compile_watch

    assert compile_watch.compile_count("jit(sharded_train_step)") == 1, (
        compile_watch.counts_by_fn()
    )
    assert compile_watch.post_warmup_count() == 0, (
        [e.as_dict() for e in compile_watch.events(last=4)]
    )
    print(f"# mixed smoke: bf16+sharded losses={trainer.train_losses} "
          f"buckets={len(plan.buckets)} "
          f"overlap={plan.overlap_fraction:.2f} OK")

    # Per-bucket comm accounting + the overlap gauge are live.
    buckets = comm_bucket_bytes()
    assert len(buckets.get("reduce_scatter", {})) == len(plan.buckets)
    assert len(buckets.get("all_gather", {})) == len(plan.buckets)
    snap = default_registry().snapshot()
    assert snap.get("train_overlap_fraction") == round(
        plan.overlap_fraction, 10
    ) or abs(
        snap.get("train_overlap_fraction", -1) - plan.overlap_fraction
    ) < 1e-9, snap.get("train_overlap_fraction")
    assert any(
        k.startswith("comm_bucket_bytes_total{") for k in snap
    ), "per-bucket gauge missing from the registry"
    print("# mixed smoke: per-bucket comm gauges + overlap fraction OK")

    # Overflow semantics: scale halves, rollback streak does not burn,
    # and the step still does not recompile.  Float batches (MLModel +
    # the reference transform) — token batches are integer and cannot
    # carry the injected NaN.
    from ml_trainer_tpu import MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    cifar = SyntheticCIFAR10(
        size=32, seed=0, transform=custom_pre_process_function()
    )
    with faults.injected("nan_grad@step=1"):
        t2 = Trainer(
            MLModel(), datasets=(cifar, cifar), epochs=1, batch_size=8,
            model_dir=os.path.join(workdir, "overflow"),
            mesh_shape={"data": 2}, metric=None,
            lr=1e-2, precision="bf16", dp_update="sharded",
        )
        t2.fit()
    assert float(t2.state.loss_scale) == s0 * 0.5, float(t2.state.loss_scale)
    assert int(jax.device_get(t2.state.bad_streak)) == 0
    assert t2.skipped_steps == [1], t2.skipped_steps
    # One more sharded step compiled (t2's own program), still no
    # steady-state recompiles anywhere in the process.
    assert compile_watch.compile_count("jit(sharded_train_step)") == 2, (
        compile_watch.counts_by_fn()
    )
    assert compile_watch.post_warmup_count() == 0
    print("# mixed smoke: overflow halves scale without burning rollback OK")
    print("MIXED_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
