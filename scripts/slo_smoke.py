#!/usr/bin/env python
"""Serving-SLO smoke leg (scripts/fastlane.sh) — ~60s on CPU.

One short end-to-end pass over the request-lifecycle tracing + SLO
telemetry + open-loop load harness, through the REAL HTTP server:

1. **Open loop through HTTP.**  A seeded Poisson schedule drives POST
   ``/v1/generate``; every request completes, the scheduled arrivals
   fire faithfully.
2. **Histograms + attainment.**  ``/metrics`` exposes the lifecycle
   latency histograms (``serving_ttft_seconds_bucket{le=...}`` with a
   non-zero ``_count``) and the ``serving_slo_attainment`` /
   ``serving_slo_burn_rate`` series; ``/slo`` returns the structured
   attainment snapshot.
3. **Trace nesting.**  Each finished request lands on the span trace as
   a ``request N`` complete event whose queue_wait / prefill / decode
   children nest by time containment.
4. **Preemption forensics.**  A pool too small for two long generations
   forces a preemption under load; the flight dump NAMES the affected
   request ids (ring ``preempt`` events + ``active_request_ids``) with
   their lifecycle timelines attached (``context.serving_requests``).

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"SLO_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.serving import (
        Server, SloPolicy, TenantLoad, poisson_schedule, run_open_loop,
    )
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.telemetry import spans
    from ml_trainer_tpu.telemetry.flight import get_recorder
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )

    # 1+2+3: open-loop Poisson schedule through the real HTTP server.
    schedule = poisson_schedule(
        rate_rps=20.0, n_requests=10, vocab_size=model.vocab_size,
        tenants={"pro": TenantLoad(weight=2.0, prompt_len=(6, 12),
                                   output_len=(3, 6)),
                 "free": TenantLoad(prompt_len=(6, 12),
                                    output_len=(3, 6))},
        seed=0,
    )
    spans.clear_trace()
    with Server(model, variables, max_batch=4, max_queue=32,
                slo=SloPolicy(ttft_ms=5000.0, tpot_ms=5000.0)) as srv:
        host, port = srv.serve_http(port=0)
        url = f"http://{host}:{port}"
        report = run_open_loop(schedule, url=url, timeout=300)
        with urllib.request.urlopen(f"{url}/metrics", timeout=30) as resp:
            prom = resp.read().decode()
        with urllib.request.urlopen(f"{url}/slo", timeout=30) as resp:
            slo = json.loads(resp.read())
    if report["n_completed"] != len(schedule) or report["n_errors"]:
        return fail(
            f"open loop incomplete: {report['n_completed']}/"
            f"{len(schedule)} done, errors {report['errors']}"
        )
    if 'serving_ttft_seconds_bucket{tenant="pro",le="0.001"}' not in prom:
        return fail("TTFT histogram bucket exposition missing from /metrics")
    for name in ("serving_ttft_seconds", "serving_tpot_seconds",
                 "serving_queue_wait_seconds", "serving_e2e_seconds"):
        if f"# TYPE {name} histogram" not in prom:
            return fail(f"{name} missing from /metrics")
    if 'serving_slo_attainment{slo="ttft",tenant="all"}' not in prom \
            or "serving_slo_burn_rate" not in prom:
        return fail("SLO attainment/burn-rate series missing from /metrics")
    if slo["requests_observed"] != len(schedule):
        return fail(
            f"/slo observed {slo['requests_observed']} of {len(schedule)}"
        )
    if not (0.0 <= slo["attainment"]["ttft"] <= 1.0):
        return fail(f"attainment out of range: {slo['attainment']}")
    evs = spans.trace_events()
    req_spans = {
        e["args"]["request"]: e for e in evs
        if e["name"].startswith("request ") and "args" in e
    }
    if len(req_spans) < len(schedule):
        return fail(
            f"{len(req_spans)} request spans for {len(schedule)} requests"
        )
    kids = [
        e for e in evs
        if e["name"] in ("queue_wait", "prefill", "decode")
        and e.get("args", {}).get("request") in req_spans
    ]
    if len(kids) < 2 * len(schedule):
        return fail(f"only {len(kids)} lifecycle child spans recorded")
    for k in kids:
        parent = req_spans[k["args"]["request"]]
        if not (parent["ts"] - 1 <= k["ts"]
                and k["ts"] + k["dur"] <= parent["ts"] + parent["dur"] + 1):
            return fail(
                f"span {k['name']} of request {k['args']['request']} "
                "does not nest inside its request span"
            )
    print(f"# slo smoke: {report['n_completed']} requests, attainment "
          f"{slo['attainment']}, {len(kids)} nested lifecycle spans")

    # 4: forced preemption under load -> flight dump names the requests.
    rng = np.random.default_rng(1)
    p1 = rng.integers(0, 1024, 9).astype(np.int32)
    p2 = rng.integers(0, 1024, 11).astype(np.int32)
    get_recorder().clear()
    with Server(model, variables, max_batch=2, kv_page_size=8,
                kv_pages=13, prefix_cache=False) as srv:
        s1 = srv.submit(p1, 40, tenant="victim")
        s2 = srv.submit(p2, 40, tenant="victim")
        s1.result(timeout=300)
        s2.result(timeout=300)
        snap = srv.metrics.snapshot()
        dump_path = get_recorder().dump("slo_smoke forced preemption")
    if snap["preemptions_total"] < 1:
        return fail("tight pool produced no preemption")
    if not dump_path:
        return fail("flight dump failed to write")
    with open(dump_path, encoding="utf-8") as fp:
        dump = json.load(fp)
    preempts = [r for r in dump["records"] if r["kind"] == "preempt"]
    if not preempts or "request" not in preempts[0]:
        return fail(f"preempt record misses request id: {preempts[:1]}")
    hurt = preempts[0]["request"]
    ctx = dump.get("context", {}).get("serving_requests", {})
    tl = next(
        (t for t in ctx.get("recent", []) + ctx.get("active", [])
         if t.get("id") == hurt), None,
    )
    if tl is None:
        return fail(
            f"request {hurt} timeline missing from dump context "
            f"({len(ctx.get('recent', []))} recent)"
        )
    if not any(e.get("event") == "preempt" for e in tl.get("events", [])):
        return fail(f"timeline of request {hurt} lacks its preempt event")
    reg = MetricsRegistry()
    srv.metrics.publish(reg)
    if "serving_preemptions_total" not in reg.prometheus_text():
        return fail("preemption counter missing from exposition")
    os.remove(dump_path)
    print(f"# slo smoke: preemption dump names request {hurt} with "
          f"{len(tl['events'])} lifecycle events")
    print("SLO_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
