#!/usr/bin/env python
"""Watchtower smoke leg (scripts/fastlane.sh) — the PR 20 tentpole end
to end against a REAL 3-process fleet slice (1 prefill + 2 decode over
HTTP), proving the observability plane is free and the alerting path is
live:

1. **Free** — with the TSDB sampling on every scrape, the dashboard
   served, and the alert engine evaluating each poll tick, the fleet
   still serves a seeded trace byte-identical to in-driver
   ``generate()`` with ZERO post-warmup compiles per worker.
2. **Live dashboard** — ``GET /dash`` on the router AND on a worker
   returns the self-contained HTML (inline sparklines, no assets).
3. **Detection** — a ``replica_slow`` chaos fault is armed in decode0's
   process via ``POST /admin/faults`` AFTER warmup; one more traffic
   pass (still byte-identical: throttled, not wrong) makes decode0's
   e2e observations jump, and a declarative severity-``page``
   :class:`AlertRule` installed at runtime
   (``quantile_over_time`` over the federated ``replica=decode0``
   series) fires within one evaluation window — producing the flight
   ``alert`` record AND a full incident bundle whose artifacts include
   ``dashboard.html`` (the TSDB snapshot at firing time) and
   ``alerts.json`` (rule states + history).

Prints ``WATCHTOWER_SMOKE OK`` / ``WATCHTOWER_SMOKE FAIL: <why>``;
non-zero exit on any violation.  CPU-only, tiny model.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

RULE = "replica_slow_e2e"


def fail(msg: str) -> int:
    print(f"WATCHTOWER_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.telemetry.alerts import AlertRule

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    rows = [
        ScheduledRequest(
            arrival_s=i * 0.02, tenant=f"tenant{i % 2}",
            prompt=rng.integers(
                0, model.vocab_size, int(rng.integers(8, 25))
            ).astype(np.int32),
            max_new_tokens=8,
        )
        for i in range(8)
    ]
    trace = schedule_from_trace(schedule_to_records(rows))
    refs = [
        [int(t) for t in np.asarray(
            generate(model, variables, s.prompt[None], s.max_new_tokens)
        )[0]]
        for s in trace
    ]

    fleet = Fleet(
        roles=["prefill", "decode", "decode"],
        model_name="gpt2_tiny", max_len=64, max_batch=2,
        kv_page_size=8, prefill_chunk=16, seed=0,
    )
    fleet.start()
    incident_root = tempfile.mkdtemp(prefix="watchtower-smoke-")
    router = fleet.make_router(
        hedging=False, metrics_scrape_interval=0.1,
        incident_dir=incident_root, incident_min_interval_s=0.0,
    )
    try:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"

        # -- leg 1: the plane is free ----------------------------------
        for _ in range(2):  # untimed: workers compile to steady state
            run_open_loop(trace, url=url, time_scale=0.0)

        def compiles():
            return {
                n: int(r._get("/v1/spec")["compiles"] or 0)
                for n, r in fleet.replicas.items()
            }

        def check_identity(client, what: str):
            if client["n_errors"]:
                return f"{client['n_errors']} client error(s) ({what})"
            for r, ref in zip(client["per_request"], refs):
                if r.get("output") != ref:
                    return (
                        f"fleet output diverged from generate() {what}"
                    )
            return None

        before = compiles()
        client = run_open_loop(trace, url=url, collect_tokens=True)
        after = compiles()
        err = check_identity(client, "with the watchtower on")
        if err:
            return fail(err)
        fresh = {n: after[n] - before[n] for n in after}
        if any(fresh.values()):
            return fail(f"post-warmup worker recompiles: {fresh}")
        print(
            f"# watchtower smoke: {len(trace)} requests byte-identical "
            "across 3 processes with TSDB + alert engine + dashboard "
            "on, 0 post-warmup compiles"
        )

        # -- leg 2: live dashboards ------------------------------------
        router.scrape_metrics(force=True)
        router._watchtower_tick()
        for name, dash_url in [
            ("router", f"{url}/dash"),
            ("decode0", f"{fleet.replicas['decode0'].url}/dash"),
        ]:
            with urllib.request.urlopen(dash_url, timeout=10) as resp:
                ctype = resp.headers.get("Content-Type", "")
                html = resp.read().decode()
            if "text/html" not in ctype:
                return fail(f"{name} /dash content-type {ctype!r}")
            if "<html" not in html or "svg" not in html:
                return fail(
                    f"{name} /dash is not the sparkline dashboard"
                )
        if f"{len(router.watchtower)}" == "0":
            return fail("router TSDB empty after scrape+tick")
        print(
            f"# watchtower smoke: GET /dash live on router + worker, "
            f"router TSDB holds {len(router.watchtower)} series"
        )

        # -- leg 3: chaos -> declarative page -> incident bundle -------
        router.add_alert_rule(AlertRule(
            RULE,
            "quantile(0.9, serving_e2e_seconds{replica=decode0}[60s])"
            " > 0.5",
            severity="page",
            description="decode0 e2e q90 regressed (replica_slow)",
        ))
        victim = fleet.replicas["decode0"]
        spec = f"replica_slow@host={victim.replica_index},secs=3"
        resp = victim._post("/admin/faults", {"spec": spec})
        if not resp.get("ok"):
            return fail(f"fault install rejected: {resp}")
        t_fault = time.monotonic()
        client = run_open_loop(trace, url=url, collect_tokens=True)
        err = check_identity(client, "under replica_slow chaos")
        if err:
            return fail(err)

        # One evaluation window: the next scrape carries the regressed
        # observations; the first evaluate over it must fire.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.scrape_metrics(force=True)
            router._watchtower_tick()
            if router.alerts.rule(RULE).firing():
                break
            time.sleep(0.1)
        else:
            return fail(
                f"rule {RULE} never fired after replica_slow "
                f"(history: {router.alerts.history()[-3:]})"
            )
        t_fired = time.monotonic() - t_fault
        fired = [
            ev for ev in router.alerts.history()
            if ev["rule"] == RULE and ev["state"] == "firing"
        ]
        if not fired:
            return fail("rule firing but no firing event in history")

        deadline = time.monotonic() + 60
        bundle = None
        while time.monotonic() < deadline:
            bundle = router.last_incident_path
            if bundle and os.path.exists(
                os.path.join(bundle, "manifest.json")
            ):
                break
            time.sleep(0.1)
        else:
            return fail("page alert never assembled an incident bundle")
        have = set(os.listdir(bundle))
        for want in ("dashboard.html", "alerts.json",
                     "flight_router.json", "manifest.json",
                     "metrics.prom"):
            if want not in have:
                return fail(f"incident bundle missing {want}")
        with open(os.path.join(bundle, "manifest.json"),
                  encoding="utf-8") as fp:
            manifest = json.load(fp)
        if RULE not in str(manifest.get("reason", "")):
            return fail(
                f"bundle reason does not name the rule: "
                f"{manifest.get('reason')!r}"
            )
        with open(os.path.join(bundle, "alerts.json"),
                  encoding="utf-8") as fp:
            alerts = json.load(fp)
        if not any(
            ev.get("rule") == RULE and ev.get("state") == "firing"
            for ev in alerts.get("history", [])
        ):
            return fail("bundle alerts.json lacks the firing event")
        with open(os.path.join(bundle, "dashboard.html"),
                  encoding="utf-8") as fp:
            dash = fp.read()
        if RULE not in dash:
            return fail(
                "bundle dashboard.html does not render the alert"
            )
        with open(os.path.join(bundle, "flight_router.json"),
                  encoding="utf-8") as fp:
            flight = fp.read()
        if '"alert"' not in flight or RULE not in flight:
            return fail(
                "router flight dump lacks the alert record"
            )
        print(
            f"# watchtower smoke: replica_slow on decode0 -> {RULE} "
            f"fired {t_fired:.1f}s after injection (value "
            f"{fired[0].get('value')}), bundle "
            f"{os.path.basename(bundle)} holds dashboard.html + "
            "alerts.json + flight alert record"
        )
    finally:
        router.close()
        fleet.stop()
    print("WATCHTOWER_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
