#!/usr/bin/env python
"""Paged-serving smoke leg (scripts/fastlane.sh) — ~60s on CPU.

One tiny end-to-end pass over the PR6 paged-KV serving subsystem, as a
standalone script so the fast lane exercises the whole stack in one
process — engine, pool, prefix cache, tenant scheduler, metrics,
flight recorder — not just the unit surfaces:

1. **Byte identity.**  Shared-prefix requests through the paged engine
   (prefix hits -> continuation prefill) match standalone ``generate()``
   byte-for-byte, greedy and ``spec_k`` alike.
2. **Prefix reuse.**  The radix cache reports hits and saved tokens.
3. **Preempt-and-requeue.**  A pool too small for two long generations
   preempts a victim, re-queues it, and both outputs still match
   ``generate()``; the flight recorder carries the ``preempt`` event
   naming tenant and cause; every page returns to the pool.
4. **Telemetry.**  ``serving_kv_pages_*`` and ``serving_tenant_*``
   series appear in the registry's Prometheus exposition.

Exits non-zero (with a reason) on any violation.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"PAGED_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Server, TenantConfig
    from ml_trainer_tpu.telemetry.flight import get_recorder
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    shared = rng.integers(0, 1024, 24).astype(np.int32)
    prompts = [
        np.concatenate(
            [shared, rng.integers(0, 1024, 2 + i).astype(np.int32)]
        )
        for i in range(4)
    ]
    refs = [
        np.asarray(generate(model, variables, p[None], 8))[0]
        for p in prompts
    ]

    # 1+2: prefix hits + greedy byte identity, tenants mixed in.  The
    # prefix cache is tenant-scoped by default (cross-tenant residency
    # is a side channel), so the 4 shared-prefix requests over tenants
    # a/b yield exactly one hit per tenant — 2 hits, 2 x 24 tokens.
    with Server(model, variables, max_batch=2, kv_page_size=8,
                tenants={"a": TenantConfig(weight=2.0),
                         "b": TenantConfig()}) as srv:
        outs = [
            srv.submit(p, 8, tenant="ab"[i % 2])
            for i, p in enumerate(prompts)
        ]
        outs = [s.result(timeout=300) for s in outs]
        snap = srv.metrics.snapshot()
        reg = MetricsRegistry()
        srv.metrics.publish(reg)
        prom = reg.prometheus_text()
    for o, r in zip(outs, refs):
        if not np.array_equal(o, r):
            return fail("paged greedy output diverged from generate()")
    if snap["prefix_hits"] < 2 or snap["prefix_tokens_saved"] < 48:
        return fail(f"prefix cache inert: {snap['prefix_hits']} hits, "
                    f"{snap['prefix_tokens_saved']} tokens saved")
    if snap["prefix_hits"] > 2:
        return fail(
            f"tenant isolation broken: {snap['prefix_hits']} hits for 4 "
            "shared-prefix requests over 2 tenants (expected 2 — one "
            "self-hit per tenant, no cross-tenant reuse)"
        )
    if "serving_kv_pages_free" not in prom:
        return fail("serving_kv_pages_free missing from /metrics")
    if 'serving_tenant_admitted{tenant="a"}' not in prom:
        return fail("per-tenant series missing from /metrics")
    print(f"# paged smoke: prefix hits={snap['prefix_hits']} "
          f"saved={snap['prefix_tokens_saved']} tokens "
          f"hit_rate={snap['prefix_hit_rate']}")

    # spec_k byte identity through page tables.
    with Server(model, variables, max_batch=2, kv_page_size=8,
                spec_k=4) as srv:
        outs = [srv.submit(p, 8) for p in prompts[:2]]
        outs = [s.result(timeout=300) for s in outs]
    for o, r in zip(outs, refs[:2]):
        if not np.array_equal(o, r):
            return fail("paged spec_k output diverged from generate()")
    print("# paged smoke: spec_k byte identity OK")

    # 3: preempt-and-requeue under a pool that cannot hold both.
    p1, p2 = (rng.integers(0, 1024, 9).astype(np.int32),
              rng.integers(0, 1024, 11).astype(np.int32))
    r1 = np.asarray(generate(model, variables, p1[None], 40))[0]
    r2 = np.asarray(generate(model, variables, p2[None], 40))[0]
    get_recorder().clear()
    with Server(model, variables, max_batch=2, kv_page_size=8,
                kv_pages=13, prefix_cache=False) as srv:
        s1 = srv.submit(p1, 40, tenant="victim")
        s2 = srv.submit(p2, 40, tenant="victim")
        o1 = s1.result(timeout=300)
        o2 = s2.result(timeout=300)
        snap = srv.metrics.snapshot()
    if not (np.array_equal(o1, r1) and np.array_equal(o2, r2)):
        return fail("preempt-resume output diverged from generate()")
    if snap["preemptions_total"] < 1:
        return fail("tight pool produced no preemption")
    if snap["kv_pages_free"] != snap["kv_pages_total"]:
        return fail(f"page leak: {snap['kv_pages_free']} free of "
                    f"{snap['kv_pages_total']} after drain")
    preempts = [
        r for r in get_recorder().records() if r["kind"] == "preempt"
    ]
    if not preempts or preempts[0].get("tenant") != "victim" \
            or "page_pressure" not in preempts[0].get("cause", ""):
        return fail(f"flight preempt forensics missing/incomplete: "
                    f"{preempts[:1]}")
    print(f"# paged smoke: preemptions={snap['preemptions_total']} "
          "resume byte identity OK, no page leaks")
    print("PAGED_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
