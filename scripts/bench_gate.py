#!/usr/bin/env python
"""Bench regression gate — the committed BENCH trajectory finally gates.

VERDICT r5 flagged that the ``BENCH_r*.json`` trajectory the driver
commits every round measures but never *enforces* anything: a PR could
halve samples/s and tier-1 would stay green.  This script closes the
loop as a fastlane leg:

1. measure a fresh headline row through the real Trainer step
   (``bench.bench_parity`` — the identical code path the committed rows
   used), best-of-``--reps`` to step over scheduler noise;
2. compare against the newest committed ``BENCH_r*.json`` row measured
   on the SAME backend (rows without an explicit ``backend`` field are
   classified by their CPU-fallback note).  Within ``--threshold``
   (default 10%) of the trajectory → pass;
3. a machine can be legitimately slower than the one that produced the
   committed rows (containers differ round to round), and a gate that
   always fails is worse than none — so when the trajectory check
   misses, the gate falls back to a MACHINE-LOCAL baseline
   (``.bench_gate_baseline.json`` in the repo root, keyed by a CPU
   fingerprint).  First contact on an unmatched machine calibrates the
   baseline and passes with a note; every later run on that machine
   fails hard when the fresh number drops >``--threshold`` below the
   recorded best.  The baseline ratchets upward on every pass, so the
   gate tightens as the machine shows what it can do.

A second leg (``gate_serve_replay``, skip with ``--skip-serve``) gates
the PR6 paged serving subsystem on a short multi-tenant shared-prefix
replay: byte identity and the zero-recompile pin are hard invariants,
the paged-vs-contiguous ratio is the machine-independent floor, and the
paged sustained tokens/s ratchets against the committed
``docs/serving_replay_cpu.json`` artifact / this machine's baseline.

A third leg (``gate_mixed``, skip with ``--skip-mixed``) gates the PR7
data-parallel hot path: finite loss and zero recompiles across the
{fp32,bf16} x {fused,sharded} matrix are hard invariants, the bucketed
reduce-scatter + sharded update must hold the fused-psum rate at fp32
(machine-independent floor), and the fp32 sharded samples/s ratchets
against ``docs/mixed_precision_cpu.json`` / this machine's baseline.

A fourth leg (``gate_pipeline``, skip with ``--skip-pipeline``) gates
the PR8 pipeline schedules: serial-fold trajectory equality and the
zero-recompile pin across every schedule row are hard invariants, the
1F1B-vs-GPipe step-rate ratio at S=4/M=8 is the machine-independent
floor, and the 1F1B steps/s ratchets against the committed
``docs/pipeline_schedules_cpu.json`` artifact / this machine's
baseline.

A fifth leg (``gate_slo``, skip with ``--skip-slo``) gates the serving
SLO harness: a short open-loop Poisson run through the real HTTP server
at the committed artifact's highest offered rate — zero recompiles and
zero client errors are hard invariants, attainment must be computed
over every request, and the sustained tokens/s at that rate ratchets
against ``docs/serving_slo_cpu.json`` / this machine's baseline.

A sixth leg (``gate_lint``, skip with ``--skip-lint``) gates the
graft-lint static analysis: the jaxpr contract checks over the traced
train/decode/pipeline programs and the AST concurrency/hygiene pack
must report no finding absent from the committed
``docs/graft_lint_baseline.json`` (zero findings on a clean tree) —
new SPMD deadlock / precision / donation / lock-order findings are
hard failures before any device runs.

An eighth leg (``gate_lora``, skip with ``--skip-lora``) gates the
batched-LoRA serving subsystem: adapter=None byte identity vs the
single-model server, zero recompiles across mixed-rank traffic and a
mid-run hot-load, full residency coverage (the pool genuinely holds the
concurrent adapter set), the 0.8x single-model busy-tokens/s floor, and
a ratchet against ``docs/serving_lora_cpu.json`` / this machine's
baseline.

A seventh leg (``gate_elastic``, skip with ``--skip-elastic``) gates
elastic training (ROADMAP #1): the drain→reshape→continue chaos run
must finish with the uninterrupted trajectory, zero steps lost and a
bit-exact-resumable history (hard invariants), the cross-process
hard-kill restart must stay within the ``save_every_steps`` steps-lost
cadence bound, and the time-to-recover rate ratchets against
``docs/elastic_chaos_cpu.json`` / this machine's baseline (elastic
threshold floored at 0.5 — wall-clock recovery breathes on shared
containers).

A ninth leg (``gate_kernels``, skip with ``--skip-kernels``) gates the
``ops/kernels/`` Pallas pass: interpret-mode bit parity for all three
kernels (paged-attention decode, fused sharded-Adam tail, int8
weight-quantized matmul), engine byte identity gather-vs-paged_kernel
and the zero-post-warmup-recompile pin are hard invariants, the
paged_kernel decode step must hold 0.5x the gather engine's rate
(machine-independent — off-TPU both run the same reference program),
and the kernel-engine decode steps/s ratchets against
``docs/kernels_cpu.json`` / this machine's ``cpu_kernels`` baseline.

Exit non-zero = regression.  Threshold override:
``ML_TRAINER_TPU_BENCH_GATE_THRESHOLD`` (fraction, e.g. ``0.15``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_FILE = os.path.join(REPO, ".bench_gate_baseline.json")

# Every gate leg --changed-only can select.  "parity" is the headline
# train-step gate; the rest match their gate_<name> function.
ALL_LEGS = frozenset({
    "parity", "serve", "mixed", "pipeline", "slo", "disagg", "lora",
    "overload", "goodput", "elastic", "lint", "fleet", "kernels",
    "deploy", "watchtower",
})

# Committed artifacts map to exactly the leg that ratchets against
# them: regenerating an artifact must re-run its gate.
_ARTIFACT_LEGS = {
    "serving_replay_cpu.json": "serve",
    "mixed_precision_cpu.json": "mixed",
    "pipeline_schedules_cpu.json": "pipeline",
    "serving_slo_cpu.json": "slo",
    "serving_disagg_cpu.json": "disagg",
    "serving_lora_cpu.json": "lora",
    "serving_chaos_cpu.json": "overload",
    "serving_fleet_cpu.json": "fleet",
    "fleet_obs_cpu.json": "fleet",
    "serving_deploy_cpu.json": "deploy",
    "memory_goodput_cpu.json": "goodput",
    "elastic_chaos_cpu.json": "elastic",
    "graft_lint_baseline.json": "lint",
    "kernels_cpu.json": "kernels",
    "watchtower_cpu.json": "watchtower",
}


def changed_files(ref: str = "origin/main",
                  repo: str = REPO):
    """Repo-relative paths changed vs ``ref`` (committed diff plus the
    working tree), or None when git cannot answer — the caller must
    treat None as "run everything"."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=repo, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return [line.strip() for line in out.stdout.splitlines()
            if line.strip()]


def legs_for_changes(files) -> set:
    """Pure mapping: changed paths -> the gate legs that must run.

    Conservative by construction — anything unrecognized selects EVERY
    leg, and a ``ml_trainer_tpu/serving/`` change selects every leg
    (the serving stack underpins most of them and shares the engine the
    parity gate times).  Docs/tests/smoke-script-only diffs select a
    strict subset (tier-1 and the smokes still cover them in the
    fastlane).  ``None`` (git unavailable) selects everything."""
    if files is None:
        return set(ALL_LEGS)
    legs: set = set()
    for path in files:
        base = os.path.basename(path)
        if path.startswith("docs/") and base in _ARTIFACT_LEGS:
            legs.add(_ARTIFACT_LEGS[base])
            continue
        if re.match(r"BENCH_r\d+\.json$", base):
            legs.add("parity")
            continue
        # Docs, tests, and the smoke scripts ride tier-1/smoke legs —
        # they cannot regress a bench number.
        if path.endswith((".md", ".rst", ".txt")) or \
                path.startswith(("docs/", "tests/")) or \
                base in (".gitignore", "LICENSE") or \
                (path.startswith("scripts/")
                 and base.endswith("_smoke.py")):
            continue
        if path.startswith("ml_trainer_tpu/serving/"):
            return set(ALL_LEGS)
        if path.startswith("ml_trainer_tpu/ops/"):
            # The kernel layer (and the ops it references) is covered by
            # its own parity/identity/recompile gate plus the sharded-
            # update matrix; the full 2700s sweep adds nothing an ops/
            # edit can regress that these two don't measure.
            legs.update({"kernels", "mixed"})
            continue
        if path.startswith("ml_trainer_tpu/resilience/"):
            legs.update({"elastic", "overload", "fleet"})
            continue
        if path.startswith("ml_trainer_tpu/telemetry/"):
            # The observability spine (registry/spans/flight/export/
            # federation/watchtower) is exercised end-to-end by the
            # legs that read it: the SLO plane, the multi-process fleet
            # (whose gate pins the federation/trace/bundle invariants),
            # the rollout gate's SLO-burn rollback, and the watchtower
            # gate (TSDB/alert-engine/dashboard overhead + detection
            # invariant).  A telemetry edit cannot move a train-step or
            # kernel number.
            legs.update({"slo", "fleet", "deploy", "watchtower"})
            continue
        if base == "graft_lint.py" and path.startswith("scripts/"):
            legs.add("lint")
            continue
        # bench.py, bench_gate.py, the model/trainer core, anything
        # else: no safe subset — run everything.
        return set(ALL_LEGS)
    return legs


def machine_fingerprint() -> str:
    """Coarse same-machine identity: CPU model x core count.  Good enough
    to tell 'this container' from 'the container that measured r05'."""
    model = ""
    try:
        with open("/proc/cpuinfo") as fp:
            for line in fp:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        import platform

        model = platform.processor() or platform.machine()
    return f"{model} x{os.cpu_count()}"


def row_backend(row: dict) -> str:
    """Backend a committed row was measured on.  Old rows predate the
    explicit field; their CPU-fallback note is the tell."""
    backend = row.get("backend")
    if backend:
        return str(backend)
    return "cpu" if "CPU fallback" in str(row.get("note") or "") else "tpu"


def committed_rows(repo: str = REPO) -> list:
    """(round, row) for every parseable committed BENCH artifact, round
    ascending."""
    out = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            data = json.load(open(path))
        except (OSError, ValueError):
            continue
        row = data.get("parsed") or {}
        if isinstance(row, dict) and isinstance(row.get("value"), (int, float)):
            out.append((int(m.group(1)), row))
    return out


def reference_for(backend: str, rows=None):
    """The newest committed row measured on ``backend`` (None if none)."""
    rows = committed_rows() if rows is None else rows
    matching = [(r, row) for r, row in rows if row_backend(row) == backend]
    return matching[-1] if matching else None


def load_baseline(backend: str, fingerprint: str,
                  path: str = BASELINE_FILE):
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    entry = data.get(backend)
    if not entry or entry.get("fingerprint") != fingerprint:
        return None
    value = entry.get("samples_per_sec")
    return float(value) if isinstance(value, (int, float)) else None


def save_baseline(backend: str, fingerprint: str, value: float,
                  path: str = BASELINE_FILE) -> None:
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        data = {}
    data[backend] = {
        "fingerprint": fingerprint,
        "samples_per_sec": round(value, 1),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(data, fp, indent=1)
    os.replace(tmp, path)


def evaluate(fresh: float, committed_ref, local_baseline,
             threshold: float) -> dict:
    """Gate decision, separated for unit testing.

    ``committed_ref``: newest same-backend committed samples/s (or None).
    ``local_baseline``: this machine's recorded best (or None).
    Pass when the fresh rate holds the committed trajectory; else fail
    against the local baseline; else calibrate (pass + record).
    """
    result = {
        "fresh_samples_per_sec": round(fresh, 1),
        "committed_reference": committed_ref,
        "local_baseline": local_baseline,
        "threshold": threshold,
    }
    if committed_ref and fresh >= (1.0 - threshold) * committed_ref:
        result.update(ok=True, decided_by="committed_trajectory")
        return result
    if local_baseline:
        ok = fresh >= (1.0 - threshold) * local_baseline
        result.update(
            ok=ok,
            decided_by="local_baseline",
            ratio_vs_baseline=round(fresh / local_baseline, 3),
        )
        return result
    if committed_ref:
        result.update(
            ok=True, decided_by="calibration",
            note="machine slower than the committed trajectory and no "
            "local baseline yet; recording this run as the baseline",
        )
        return result
    result.update(
        ok=True, decided_by="no_reference",
        note="no committed row for this backend; nothing to gate against",
    )
    return result


def committed_serve_reference(repo: str = REPO):
    """Paged sustained tokens/s from the committed multi-tenant replay
    artifact (docs/serving_replay_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_replay_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("paged") or {}).get("tokens_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_serve_replay(threshold: float, backend: str, fp: str) -> dict:
    """The paged-serving regression gate (PR6): a short multi-tenant
    shared-prefix replay, paged vs contiguous, gated three ways —

    1. **Invariants** (hard): greedy output byte-identical between the
       engines, and no compiles during the paged timed pass.
    2. **Paged-vs-contiguous ratio** (machine-independent): the paged
       engine must hold >= ``1 - threshold`` of the contiguous rate on
       the prefix-heavy trace (the committed artifact shows it WINNING;
       the gate's looser bound just absorbs scheduler noise).
    3. **Trajectory/local baseline** on the paged tokens/s, with the
       same calibrate-then-ratchet fallback the parity gate uses.
    """
    import bench

    result = bench.bench_serve_replay(
        n_requests=24, mean_interarrival=0.004, spec_check=False,
    )
    out = {
        "paged_tokens_per_sec": result["paged"]["tokens_per_sec"],
        "contiguous_tokens_per_sec": result["contiguous"]["tokens_per_sec"],
        "speedup": result["speedup"],
        "ttft_p99_ratio": result["ttft_p99_ratio"],
        "prefix_hit_rate": result["paged"]["prefix_hit_rate"],
        "threshold": threshold,
    }
    if not result["greedy_byte_identical"]:
        out.update(ok=False, decided_by="identity",
                   error="paged output diverged from contiguous")
        return out
    if not result["paged"]["compiled_programs_constant"]:
        out.update(ok=False, decided_by="zero_recompile",
                   error="paged replay compiled new programs mid-traffic")
        return out
    if result["speedup"] < 1.0 - threshold:
        out.update(
            ok=False, decided_by="paged_vs_contiguous",
            error=f"paged engine at {result['speedup']:.2f}x contiguous "
            f"on the shared-prefix trace (floor {1.0 - threshold:.2f}x)",
        )
        return out
    committed = committed_serve_reference()
    serve_key = f"{backend}_serve_paged"
    baseline = load_baseline(serve_key, fp)
    decision = evaluate(
        float(result["paged"]["tokens_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            serve_key, fp,
            max(float(result["paged"]["tokens_per_sec"]), baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"paged {result['paged']['tokens_per_sec']} tokens/s is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_mixed_reference(repo: str = REPO):
    """fp32 sharded-update samples/s from the committed mixed-precision
    artifact (docs/mixed_precision_cpu.json), or None."""
    path = os.path.join(repo, "docs", "mixed_precision_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    for row in data.get("rows", []):
        if (row.get("precision"), row.get("dp_update")) == (
            "fp32", "sharded"
        ) and isinstance(row.get("samples_per_sec"), (int, float)):
            return float(row["samples_per_sec"]), data
    return None


def gate_mixed(threshold: float, backend: str, fp: str) -> dict:
    """The mixed-precision / sharded-update regression gate: a short
    {fp32,bf16} x {fused,sharded} matrix on the virtual 8-device mesh,
    gated three ways —

    1. **Invariants** (hard): finite loss on every row and zero
       recompiles during every timed pass.
    2. **Sharded-vs-fused ratio** (machine-independent): the bucketed
       reduce-scatter + sharded update must hold >= ``1 - threshold`` of
       the fused-psum rate at fp32 (the committed artifact shows it
       WINNING ~1.8x on CPU — the optimizer update runs on 1/8 of the
       params; the gate's bound just absorbs scheduler noise).
    3. **Trajectory/local baseline** on the fp32 sharded samples/s, with
       the same calibrate-then-ratchet fallback the parity gate uses.
    """
    import bench

    result = bench.bench_mixed(n_devices=8, iters=5, warmup=2, reps=1)
    if result.get("error"):
        return {"ok": False, "decided_by": "worker", "error": result["error"]}
    rows = result["rows"]
    out = {
        "sharded_vs_fused_fp32": result["sharded_vs_fused_fp32"],
        "sharded_vs_fused_bf16": result["sharded_vs_fused_bf16"],
        "bf16_sharded_vs_fp32_fused": result["bf16_sharded_vs_fp32_fused"],
        "threshold": threshold,
    }
    bad = [r for r in rows if not r["loss_finite"]]
    if bad:
        out.update(ok=False, decided_by="finite_loss",
                   error=f"non-finite loss on {len(bad)} row(s)")
        return out
    bad = [r for r in rows if not r["compiled_programs_constant"]]
    if bad:
        out.update(ok=False, decided_by="zero_recompile",
                   error="mixed rows compiled new programs mid-run")
        return out
    if result["sharded_vs_fused_fp32"] < 1.0 - threshold:
        out.update(
            ok=False, decided_by="sharded_vs_fused",
            error=f"sharded update at {result['sharded_vs_fused_fp32']:.2f}x "
            f"fused at fp32 (floor {1.0 - threshold:.2f}x)",
        )
        return out
    sharded = next(
        r for r in rows
        if (r["precision"], r["dp_update"]) == ("fp32", "sharded")
    )
    out["fp32_sharded_samples_per_sec"] = sharded["samples_per_sec"]
    committed = committed_mixed_reference()
    mixed_key = f"{backend}_train_mixed"
    baseline = load_baseline(mixed_key, fp)
    decision = evaluate(
        float(sharded["samples_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            mixed_key, fp,
            max(float(sharded["samples_per_sec"]), baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"fp32 sharded {sharded['samples_per_sec']} samples/s is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_pipeline_reference(repo: str = REPO):
    """1F1B S=4/M=8 steps/s from the committed pipeline-schedule matrix
    (docs/pipeline_schedules_cpu.json), or None."""
    path = os.path.join(repo, "docs", "pipeline_schedules_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    for row in data.get("rows", []):
        if (row.get("schedule"), row.get("n_stage_devices"),
                row.get("n_micro"), row.get("remat")) == ("1f1b", 4, 8,
                                                          False):
            ms = row.get("step_ms")
            if isinstance(ms, (int, float)) and ms > 0:
                return 1e3 / float(ms), data
    return None


def gate_pipeline(threshold: float, backend: str, fp: str) -> dict:
    """The pipeline-schedule regression gate (PR8): the schedule matrix
    on a virtual 4-device stage mesh, gated three ways —

    1. **Invariants** (hard): every schedule's value AND grad equal the
       serial fold (the trajectory-equality discipline), and zero
       recompiles on every row.
    2. **1F1B-vs-GPipe ratio** (machine-independent): 1F1B must hold
       >= ``1 - threshold`` of GPipe's step rate at S=4/M=8 (the
       committed artifact shows it WINNING — GPipe burns bubble slots on
       garbage compute; the gate's looser bound absorbs scheduler
       noise).
    3. **Trajectory/local baseline** on the 1F1B S=4/M=8 steps/s, with
       the same calibrate-then-ratchet fallback the parity gate uses.
    """
    import bench

    result = bench.bench_pipeline(iters=10, warmup=3, reps=1)
    if result.get("error"):
        return {"ok": False, "decided_by": "worker",
                "error": result["error"]}
    rows = result["rows"]
    out = {
        "gpipe_over_1f1b_s4_m8": result["gpipe_over_1f1b_s4_m8"],
        "threshold": threshold,
    }
    bad = [r for r in rows if not r["serial_equal"]]
    if bad:
        out.update(
            ok=False, decided_by="trajectory_equality",
            error=f"{len(bad)} schedule row(s) diverged from the serial "
            f"fold: {[(r['schedule'], r['n_stages'], r['n_micro'], r['remat']) for r in bad]}",
        )
        return out
    bad = [r for r in rows if not r["compiled_programs_constant"]]
    if bad:
        out.update(ok=False, decided_by="zero_recompile",
                   error="pipeline rows compiled new programs mid-run")
        return out
    ratio = result["gpipe_over_1f1b_s4_m8"]
    if ratio is not None and ratio < 1.0 - threshold:
        out.update(
            ok=False, decided_by="1f1b_vs_gpipe",
            error=f"1f1b at {ratio:.2f}x gpipe step rate at S=4/M=8 "
            f"(floor {1.0 - threshold:.2f}x)",
        )
        return out
    f1 = next(
        (r for r in rows
         if (r["schedule"], r["n_stage_devices"], r["n_micro"],
             r["remat"]) == ("1f1b", 4, 8, False)), None,
    )
    if f1 is None or not f1.get("step_ms"):
        out.update(ok=False, decided_by="worker",
                   error="1f1b S=4/M=8 row missing from the matrix")
        return out
    fresh = 1e3 / float(f1["step_ms"])
    out["f1b_steps_per_sec"] = round(fresh, 1)
    committed = committed_pipeline_reference()
    key = f"{backend}_train_pipeline"
    baseline = load_baseline(key, fp)
    decision = evaluate(
        fresh, committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(key, fp, max(fresh, baseline or 0.0))
    elif "error" not in out:
        out["error"] = (
            f"1f1b {round(fresh, 1)} steps/s is >{threshold * 100:.0f}% "
            f"below this machine's baseline {baseline}"
        )
    return out


def committed_slo_reference(repo: str = REPO):
    """(highest offered rate, its tokens/s) from the committed SLO sweep
    artifact (docs/serving_slo_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_slo_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    rows = [
        r for r in data.get("rates", [])
        if isinstance(r.get("offered_rps"), (int, float))
        and isinstance(r.get("tokens_per_sec"), (int, float))
    ]
    if not rows:
        return None
    top = max(rows, key=lambda r: r["offered_rps"])
    return float(top["offered_rps"]), float(top["tokens_per_sec"]), data


def gate_slo(threshold: float, backend: str, fp: str) -> dict:
    """The serving-SLO regression gate: a short open-loop Poisson run
    through the real HTTP server at the committed artifact's highest
    offered rate, gated three ways —

    1. **Invariants** (hard): zero recompiles during the timed pass
       (compile_watch-pinned inside ``bench_slo``), zero client errors,
       and SLO attainment computed over every scheduled request.
    2. **Attainment sanity** (machine-independent): TPOT attainment at
       the saturating rate must stay positive — a zero means decode
       ticks themselves blew the budget, which is a throughput
       collapse, not queueing.
    3. **Trajectory/local baseline** on the sustained tokens/s at the
       highest rate, with the same calibrate-then-ratchet fallback the
       parity gate uses.
    """
    import bench

    committed = committed_slo_reference()
    top_rate = committed[0] if committed else 720.0
    result = bench.bench_slo(rates=(top_rate,), n_requests=24)
    row = result["rates"][0]
    server = row["server"]
    out = {
        "offered_rps": row["offered_rps"],
        "tokens_per_sec": row["tokens_per_sec"],
        "ttft_p99_ms": server["ttft_ms"]["p99"],
        "tpot_p99_ms": server["tpot_ms"]["p99"],
        "attainment": server["attainment"],
        "threshold": threshold,
    }
    if not row["zero_recompiles"]:
        out.update(ok=False, decided_by="zero_recompile",
                   error="compiles observed during the timed SLO pass: "
                   + str(row.get("recompile_error")))
        return out
    if row["n_errors"]:
        out.update(ok=False, decided_by="client_errors",
                   error=f"{row['n_errors']} client error(s): "
                   + "; ".join(row["client"]["errors"]))
        return out
    if server["n_requests"] < row["n_requests"]:
        out.update(
            ok=False, decided_by="attainment_coverage",
            error=f"attainment computed over {server['n_requests']} of "
            f"{row['n_requests']} requests",
        )
        return out
    if server["attainment"]["tpot"] <= 0.0:
        out.update(
            ok=False, decided_by="tpot_collapse",
            error="TPOT attainment 0 at the saturating rate — decode "
            "ticks themselves blow the budget",
        )
        return out
    slo_key = f"{backend}_serve_slo"
    baseline = load_baseline(slo_key, fp)
    decision = evaluate(
        float(row["tokens_per_sec"]),
        committed[1] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            slo_key, fp, max(float(row["tokens_per_sec"]), baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"slo sweep {row['tokens_per_sec']} tokens/s at "
            f"{top_rate} rps is >{threshold * 100:.0f}% below this "
            f"machine's baseline {baseline}"
        )
    return out


def perf_attribution(committed_path: str, fresh: dict,
                     top: int = 12) -> str:
    """The ranked what-changed table (scripts/perf_diff.py) between a
    leg's committed artifact and its fresh result — printed under a
    failed ratchet so the failure names WHERE the regression lives
    (goodput buckets, comm bytes, compile counts, latency percentiles,
    kv/adapter pressure) instead of just the one gated scalar."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import perf_diff

        rows = perf_diff.diff_leaves(
            perf_diff.load_leaves(committed_path),
            perf_diff.flatten(fresh),
        )
        return perf_diff.format_table(rows, top=top)
    except Exception as e:  # noqa: BLE001 — attribution never masks the fail
        return f"(perf attribution unavailable: {e})"


def committed_watchtower_reference(repo: str = REPO):
    """Registry-sweep rate from the committed watchtower artifact
    (docs/watchtower_cpu.json), or None."""
    path = os.path.join(repo, "docs", "watchtower_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = data.get("sample_ops_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_watchtower(threshold: float, backend: str, fp: str) -> dict:
    """The watchtower regression gate: the in-process TSDB + alert
    engine + dashboard micro-bench (pure host), gated —

    1. **Invariants** (hard): the injected TTFT regression fires the
       ``quantile_over_time`` rule on the FIRST evaluation after the
       regressed samples land (detection latency = one sample tick +
       one eval tick, never a window), rings stay bounded at capacity,
       and the dump -> load round-trip is exact.
    2. **Trajectory/local baseline** on ``sample_ops_per_sec`` (full
       registry sweeps per second — what the TSDB costs every publish
       cadence), with the calibrate-then-ratchet fallback the parity
       gate uses.  On a ratchet fail the perf_diff attribution table
       vs the committed artifact prints with the verdict.
    """
    import bench

    result = bench.bench_watchtower()
    out = {
        "sample_ops_per_sec": result["sample_ops_per_sec"],
        "sample_mean_ms": (result.get("sample") or {}).get("mean_ms"),
        "alert_eval_mean_ms":
            (result.get("alert_eval") or {}).get("mean_ms"),
        "dashboard_render_mean_ms":
            (result.get("dashboard_render") or {}).get("mean_ms"),
        "series": result.get("series"),
        "detection": result.get("detection"),
        "threshold": threshold,
    }
    if result.get("error"):
        out.update(ok=False, decided_by="invariants",
                   error=result["error"])
        return out
    committed = committed_watchtower_reference()
    wt_key = f"{backend}_watchtower"
    baseline = load_baseline(wt_key, fp)
    decision = evaluate(
        float(result["sample_ops_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            wt_key, fp,
            max(float(result["sample_ops_per_sec"]), baseline or 0.0),
        )
    else:
        if "error" not in out:
            out["error"] = (
                f"watchtower {result['sample_ops_per_sec']} registry "
                f"sweeps/s is >{threshold * 100:.0f}% below this "
                f"machine's baseline {baseline}"
            )
        if committed:
            out["attribution"] = perf_attribution(
                os.path.join(REPO, "docs", "watchtower_cpu.json"),
                result,
            )
    return out


def committed_lora_reference(repo: str = REPO):
    """LoRA-leg busy tokens/s from the committed batched-adapter
    artifact (docs/serving_lora_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_lora_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("lora") or {}).get("tokens_per_sec_busy")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_lora(threshold: float, backend: str, fp: str) -> dict:
    """The batched-LoRA serving regression gate: a short run of the
    64-adapter leg vs the single-model baseline on the identical
    schedule, gated —

    1. **Invariants** (hard): every ``adapter=None`` request's output
       byte-identical to the single-model server's, zero compiles
       during both timed passes (hot-load and mixed-rank traffic
       included), zero client errors, every adapter actually resident
       (the pool genuinely held n_adapters concurrently), and the
       mid-run hot-load served tokens.
    2. **Ratio floor** (machine-independent): LoRA busy tokens/s >=
       0.8x the single-model baseline — the ROADMAP pin.  Best-of-2:
       busy-rate on a shared container breathes ~10%, and one clean
       rep proves the program can hold the floor.
    3. **Trajectory/local baseline** on the LoRA busy tokens/s, with
       the calibrate-then-ratchet fallback the parity gate uses.
    """
    import bench

    result = bench.bench_serve_lora()
    if (
        not result.get("error")
        and result["tokens_per_sec_ratio"] < 0.8
    ):
        retry = bench.bench_serve_lora()
        if retry["tokens_per_sec_ratio"] > result["tokens_per_sec_ratio"]:
            result = retry
    out = {
        "lora_tokens_per_sec_busy": result["lora"]["tokens_per_sec_busy"],
        "single_model_tokens_per_sec_busy":
            result["single_model"]["tokens_per_sec_busy"],
        "tokens_per_sec_ratio": result["tokens_per_sec_ratio"],
        "adapters_resident": result["adapters_resident"],
        "hot_load_tokens": result["hot_load_tokens"],
        "threshold": threshold,
    }
    if not result["base_requests_byte_identical"]:
        out.update(ok=False, decided_by="identity",
                   error="adapter=None output diverged from the "
                   "single-model server")
        return out
    if not result["zero_recompiles"]:
        out.update(ok=False, decided_by="zero_recompile",
                   error="compiles observed during a timed LoRA pass: "
                   + str(result.get("recompile_error")))
        return out
    n_err = result["lora"]["n_errors"] + result["single_model"]["n_errors"]
    if n_err:
        out.update(ok=False, decided_by="client_errors",
                   error=f"{n_err} client error(s) across legs")
        return out
    if result["adapters_resident"] < result["n_adapters"]:
        out.update(
            ok=False, decided_by="residency_coverage",
            error=f"only {result['adapters_resident']} of "
            f"{result['n_adapters']} adapters resident — the pool never "
            "actually held the concurrent set",
        )
        return out
    if not result["hot_load_tokens"]:
        out.update(ok=False, decided_by="hot_load",
                   error="mid-run hot-load served no tokens")
        return out
    if result["tokens_per_sec_ratio"] < 0.8:
        out.update(
            ok=False, decided_by="ratio_floor",
            error=f"LoRA busy tokens/s {result['tokens_per_sec_ratio']}"
            "x single-model is below the 0.8x ROADMAP floor",
        )
        return out
    committed = committed_lora_reference()
    lora_key = f"{backend}_serve_lora"
    baseline = load_baseline(lora_key, fp)
    decision = evaluate(
        float(result["lora"]["tokens_per_sec_busy"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            lora_key, fp,
            max(float(result["lora"]["tokens_per_sec_busy"]),
                baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"lora {result['lora']['tokens_per_sec_busy']} busy tokens/s "
            f"is >{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_disagg_reference(repo: str = REPO):
    """Disaggregated tokens/s from the committed router artifact
    (docs/serving_disagg_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_disagg_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("disagg") or {}).get("tokens_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_disagg(threshold: float, backend: str, fp: str) -> dict:
    """The disaggregated-serving regression gate: a short run of the
    recorded-trace replay through BOTH router topologies, gated —

    1. **Invariants** (hard): every request's output byte-identical
       between the disaggregated and colocated topologies, zero
       compiles during either timed pass, zero client errors, and
       migrations actually flowed (a disagg run with no migrations is
       a colocated run wearing the wrong label).
    2. **Trajectory/local baseline** on the disaggregated tokens/s,
       with the same calibrate-then-ratchet fallback the parity gate
       uses.  (The p99-TTFT WIN is pinned by the committed artifact —
       a short gate run is too noisy to re-litigate it, so the gate
       records the ratio without failing on it.)
    """
    import bench

    result = bench.bench_serve_disagg(n_requests=32)
    out = {
        "disagg_tokens_per_sec": result["disagg"]["tokens_per_sec"],
        "colocated_tokens_per_sec": result["colocated"]["tokens_per_sec"],
        "ttft_p99_ratio": result["ttft_p99_ratio"],
        "migrations": result["disagg"]["migrations"],
        "kv_migrated_bytes": result["disagg"]["kv_migrated_bytes"],
        "threshold": threshold,
    }
    if not result["byte_identical"]:
        out.update(ok=False, decided_by="identity",
                   error="disaggregated output diverged from colocated")
        return out
    if not result["zero_recompiles"]:
        out.update(
            ok=False, decided_by="zero_recompile",
            error="compiles observed during a timed router pass: "
            + str(result["disagg"].get("recompile_error")
                  or result["colocated"].get("recompile_error")),
        )
        return out
    n_err = result["disagg"]["n_errors"] + result["colocated"]["n_errors"]
    if n_err:
        out.update(ok=False, decided_by="client_errors",
                   error=f"{n_err} client error(s) across topologies")
        return out
    if result["disagg"]["migrations"] < result["n_requests"]:
        out.update(
            ok=False, decided_by="migration_coverage",
            error=f"only {result['disagg']['migrations']} migration(s) "
            f"for {result['n_requests']} requests — the disagg leg is "
            "not actually disaggregating",
        )
        return out
    committed = committed_disagg_reference()
    disagg_key = f"{backend}_serve_disagg"
    baseline = load_baseline(disagg_key, fp)
    decision = evaluate(
        float(result["disagg"]["tokens_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            disagg_key, fp,
            max(float(result["disagg"]["tokens_per_sec"]),
                baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"disaggregated {result['disagg']['tokens_per_sec']} "
            f"tokens/s is >{threshold * 100:.0f}% below this machine's "
            f"baseline {baseline}"
        )
    return out


def committed_fleet_reference(repo: str = REPO):
    """Fleet tokens/s from the committed multi-process fleet artifact
    (docs/serving_fleet_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_fleet_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("fleet") or {}).get("tokens_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_fleet(threshold: float, backend: str, fp: str) -> dict:
    """The multi-process fleet regression gate: a short run of the
    fleet bench (4 worker PROCESSES behind the socket router), gated —

    1. **Invariants** (hard): every output byte-identical to in-driver
       ``generate()`` — including the streams redistributed across a
       real mid-stream ``SIGKILL`` — zero post-warmup compiles in
       EVERY worker process (each worker's own ``compile_watch`` count
       via ``/v1/spec``), zero client errors (refusals must be
       structured, never hangs), socket migrations actually flowed,
       chunked prefill actually engaged on the long-prompt mix, and
       the autoscaler respawned the killed worker as a fresh process.
       Plus the observability-plane invariants on a second, live
       3-process fleet (``bench_fleet_obs``): labelled federated
       worker series, idempotent re-scrape, a causally ordered
       multi-lane merged trace, and a complete incident bundle —
       with byte identity and zero recompiles intact under the plane.
    2. **Trajectory/local baseline** on the chunked fleet's mix
       tokens/s, calibrate-then-ratchet as the other gates.  (The
       chunked-TTFT win and the 0.9x tokens floor are pinned by the
       committed artifact; the short gate run records the ratios
       without re-litigating them against scheduler noise.)
    """
    import bench

    result = bench.bench_serve_fleet(n_requests=24)
    chaos = result.get("chaos") or {}
    out = {
        "fleet_tokens_per_sec": result["fleet"]["tokens_per_sec"],
        "short_only_tokens_per_sec":
            result["short_only"]["tokens_per_sec"],
        "chunked_ttft_ratio": result["chunked_ttft_ratio"],
        "chunked_tokens_ratio": result["chunked_tokens_ratio"],
        "migrations": result["fleet"]["migrations"],
        "kv_migrated_bytes": result["fleet"]["kv_migrated_bytes"],
        "prefill_chunks": result["fleet"]["prefill_chunks"],
        "chaos_redistributes": chaos.get("redistributes"),
        "respawned_pid": chaos.get("respawned_pid"),
        "threshold": threshold,
    }
    if not result["byte_identical"]:
        out.update(ok=False, decided_by="identity",
                   error="fleet output diverged from generate() "
                   "(including post-SIGKILL streams)")
        return out
    if not result["zero_recompiles"]:
        out.update(
            ok=False, decided_by="zero_recompile",
            error="worker-process compiles observed during a timed "
            "pass: " + json.dumps({
                m: result[m].get("worker_compiles_timed")
                for m in ("fleet", "short_only", "unchunked")
            }),
        )
        return out
    n_err = sum(
        result[m]["n_errors"]
        for m in ("fleet", "short_only", "unchunked")
    )
    if n_err:
        out.update(ok=False, decided_by="client_errors",
                   error=f"{n_err} client error(s) across fleet legs")
        return out
    if result["fleet"]["migrations"] < 1 or \
            result["fleet"]["kv_migrated_bytes"] <= 0:
        out.update(
            ok=False, decided_by="migration_coverage",
            error="no socket KV migration flowed — the fleet leg is "
            "not actually disaggregating across processes",
        )
        return out
    if result["fleet"]["prefill_chunks"] < 1:
        out.update(ok=False, decided_by="chunk_coverage",
                   error="chunked prefill never engaged on the "
                   "long-prompt mix")
        return out
    if chaos.get("respawned_pid") is None or \
            not chaos.get("byte_identical"):
        out.update(
            ok=False, decided_by="chaos_recovery",
            error=f"SIGKILL recovery failed: {chaos}",
        )
        return out
    # Fleet observability plane (hard): federation labels, idempotent
    # re-scrape, a >= 2-lane causally ordered merged trace, and a
    # complete incident bundle must hold on a LIVE fleet — with byte
    # identity and zero recompiles intact under the plane — not just
    # in the committed artifact.
    obs = bench.bench_fleet_obs(n_requests=8, scrape_iters=5)
    out["obs"] = {
        k: obs.get(k) for k in (
            "federated_labels_ok", "idempotent_rescrape",
            "trace_lanes", "bundle_ok", "byte_identical",
            "zero_recompiles",
        )
    }
    if obs.get("error"):
        out.update(ok=False, decided_by="observability_plane",
                   error=f"fleet observability plane: {obs['error']}")
        return out
    committed = committed_fleet_reference()
    fleet_key = f"{backend}_serve_fleet"
    baseline = load_baseline(fleet_key, fp)
    decision = evaluate(
        float(result["fleet"]["tokens_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            fleet_key, fp,
            max(float(result["fleet"]["tokens_per_sec"]),
                baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"fleet {result['fleet']['tokens_per_sec']} tokens/s is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_deploy_reference(repo: str = REPO):
    """Post-rollback fleet tokens/s from the committed live-rollout
    artifact (docs/serving_deploy_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_deploy_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("final") or {}).get("tokens_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_deploy(threshold: float, backend: str, fp: str) -> dict:
    """The live-rollout regression gate: a run of the deploy bench
    (train -> export -> canary deploy mid-load, then a forced canary
    regression), gated —

    1. **Invariants** (hard): the healthy deploy reaches ``done`` and
       the forced regression reaches ``rolled_back`` within one burn
       window, restoring the pre-deploy replica set; zero client
       errors in every leg (no dropped streams across spawn, split,
       ramp, promote, drain and rollback); every output byte-identical
       to ``generate()`` on the generation that served it; the steady
       fleet's per-process compile counts unchanged through both
       deploys and the final pass; the served weights fingerprint
       equals the export manifest's.
    2. **Trajectory/local baseline** on the post-rollback fleet's
       tokens/s (the ``final`` pass), calibrate-then-ratchet as the
       other gates.
    """
    import bench

    result = bench.bench_serve_deploy(n_requests=16)
    dep = result.get("deploy") or {}
    rb = result.get("rollback") or {}
    fin = result.get("final") or {}
    out = {
        "deploy_state": dep.get("state"),
        "deploy_s": dep.get("deploy_s"),
        "rollback_state": rb.get("state"),
        "rollback_s": rb.get("rollback_s"),
        "rollback_cause": rb.get("rollback_cause"),
        "final_tokens_per_sec": fin.get("tokens_per_sec"),
        "fingerprint_match": result.get("fingerprint_match"),
        "threshold": threshold,
    }
    if dep.get("state") != "done":
        out.update(ok=False, decided_by="deploy_verdict",
                   error=f"healthy deploy ended "
                   f"'{dep.get('state')}', not done")
        return out
    if rb.get("state") != "rolled_back" or rb.get("rollback_s") is None:
        out.update(ok=False, decided_by="rollback_verdict",
                   error=f"forced regression ended "
                   f"'{rb.get('state')}' (rollback_s "
                   f"{rb.get('rollback_s')}), not a burn-driven "
                   "rollback")
        return out
    if rb["rollback_s"] > result.get("rollback_within_window_s",
                                     float("inf")):
        out.update(ok=False, decided_by="rollback_latency",
                   error=f"rollback took {rb['rollback_s']}s — "
                   "outside one burn window")
        return out
    n_err = (dep.get("n_client_errors", 1) + rb.get("n_client_errors", 1)
             + fin.get("n_errors", 1))
    if n_err:
        out.update(ok=False, decided_by="client_errors",
                   error=f"{n_err} client error(s) — streams dropped "
                   "during a rollout")
        return out
    if not (dep.get("byte_identical") and rb.get("byte_identical")
            and fin.get("byte_identical")):
        out.update(ok=False, decided_by="identity",
                   error="output diverged from generate() during a "
                   "rollout")
        return out
    if not (dep.get("zero_steady_recompiles")
            and rb.get("zero_steady_recompiles")
            and fin.get("zero_recompiles")):
        out.update(ok=False, decided_by="zero_recompile",
                   error="steady-fleet compiles observed during a "
                   "deploy: " + json.dumps({
                       "deploy": dep.get("steady_fleet_compiles"),
                       "rollback": rb.get("steady_fleet_compiles"),
                       "final": fin.get("worker_compiles_timed"),
                   }))
        return out
    if not result.get("fingerprint_match"):
        out.update(ok=False, decided_by="fingerprint",
                   error="served weights fingerprint != export "
                   "manifest")
        return out
    committed = committed_deploy_reference()
    deploy_key = f"{backend}_serve_deploy"
    baseline = load_baseline(deploy_key, fp)
    decision = evaluate(
        float(fin["tokens_per_sec"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            deploy_key, fp,
            max(float(fin["tokens_per_sec"]), baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"post-rollback fleet {fin['tokens_per_sec']} tokens/s is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_overload_reference(repo: str = REPO):
    """Mitigated TTFT attainment from the committed serving-chaos
    artifact (docs/serving_chaos_cpu.json), or None."""
    path = os.path.join(repo, "docs", "serving_chaos_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("mitigated") or {}).get("ttft_attainment")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_overload(threshold: float, backend: str, fp: str) -> dict:
    """The overload/chaos regression gate: a short run of the serving
    chaos leg (1-of-4 replicas killed + one slowed mid-run, recorded
    trace open-loop at saturating load, with vs without the
    autoscaler + hedging + breaker + ladder stack), gated —

    1. **Invariants** (hard): zero byte-identity regressions on
       surviving streams (degraded outputs must equal their
       un-degraded prefix), zero compiles during either chaos leg,
       every shed/failed request structured (JSON status + cause,
       retry_after on sheds), migrations actually flowed, and the
       mitigation stack beat the no-mitigation baseline by >= 1.3x
       TTFT attainment (the committed artifact pins the full >= 2x
       win; a short gate run keeps a looser floor against scheduler
       noise).
    2. **Chaos-attainment ratchet**: the mitigated leg's TTFT
       attainment vs the committed artifact / this machine's recorded
       best, the calibrate-then-ratchet fallback the other gates use.
    """
    import bench

    result = bench.bench_serve_chaos(n_requests=48, slow_secs=8.0)
    out = {
        "baseline_attainment": result["baseline"]["ttft_attainment"],
        "mitigated_attainment": result["mitigated"]["ttft_attainment"],
        "attainment_ratio": result["attainment_ratio"],
        "hedges": result["mitigated"]["hedges"],
        "autoscaler_actions": result["run_report"]["autoscaler_actions"],
        "threshold": threshold,
    }
    if not result["byte_identity_ok"]:
        out.update(ok=False, decided_by="identity",
                   error="surviving streams diverged from reference")
        return out
    if not result["zero_recompiles"]:
        out.update(
            ok=False, decided_by="zero_recompile",
            error="compiles observed during a chaos leg: "
            + str(result["baseline"].get("recompile_error")
                  or result["mitigated"].get("recompile_error")),
        )
        return out
    if not result["all_failures_structured"]:
        out.update(
            ok=False, decided_by="structured_errors",
            error=f"unstructured failures: baseline "
            f"{result['baseline']['unstructured_failures']}, mitigated "
            f"{result['mitigated']['unstructured_failures']}",
        )
        return out
    if result["mitigated"]["migrations"] < result["n_requests"]:
        out.update(
            ok=False, decided_by="migration_coverage",
            error=f"only {result['mitigated']['migrations']} "
            f"migration(s) for {result['n_requests']} requests",
        )
        return out
    if result["attainment_ratio"] < 1.3:
        out.update(
            ok=False, decided_by="mitigation_floor",
            error=f"mitigated attainment only "
            f"{result['attainment_ratio']}x baseline under chaos "
            "(gate floor 1.3x; the committed artifact pins 2x)",
        )
        return out
    committed = committed_overload_reference()
    key = f"{backend}_serve_chaos"
    baseline = load_baseline(key, fp)
    decision = evaluate(
        float(result["mitigated"]["ttft_attainment"]),
        committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            key, fp,
            max(float(result["mitigated"]["ttft_attainment"]),
                baseline or 0.0),
        )
    elif "error" not in out:
        out["error"] = (
            f"mitigated chaos attainment "
            f"{result['mitigated']['ttft_attainment']} is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_goodput_reference(repo: str = REPO):
    """The committed memory/goodput artifact
    (docs/memory_goodput_cpu.json), or None."""
    path = os.path.join(repo, "docs", "memory_goodput_cpu.json")
    try:
        return json.load(open(path))
    except (OSError, ValueError):
        return None


def gate_goodput(threshold: float) -> dict:
    """The memory-ledger / goodput / recompile gate (the third
    observability pillar): re-runs ``scripts/memory_smoke.py`` in a
    subprocess (it needs its own 2-virtual-device process) and enforces

    1. **Invariants** (hard): the smoke itself passes — analytic
       ledger within 10% of the measured per-device state bytes on the
       pure-DP / ZeRO-1 / 2-stage-pipeline legs, goodput buckets
       reconstruct the wall-clock, ZERO post-warmup compiles;
    2. **Goodput floor**: ``train_goodput_fraction`` >= 0.02 (compiles
       legitimately dominate a tiny CPU dryrun; the floor catches a
       stall, not noise);
    3. **Ledger trajectory** (machine-independent): the analytic bytes
       per config match the committed artifact EXACTLY — the shapes are
       deterministic, so any drift is a formula or state-layout change
       that must arrive as a deliberate artifact update.
    """
    import subprocess

    script = os.path.join(REPO, "scripts", "memory_smoke.py")
    try:
        proc = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            timeout=280, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "decided_by": "worker",
                "error": "memory_smoke.py timed out"}
    line = next(
        (ln for ln in proc.stdout.splitlines()
         if ln.startswith("MEMORY_SMOKE_RESULT ")), None,
    )
    if proc.returncode != 0 or line is None:
        tail = (proc.stdout + proc.stderr).strip().splitlines()[-8:]
        return {"ok": False, "decided_by": "invariants",
                "error": "memory_smoke failed: " + " | ".join(tail)}
    result = json.loads(line[len("MEMORY_SMOKE_RESULT "):])
    out = {
        "configs": result["configs"],
        "goodput_fraction": result["goodput"]["fraction"],
        "post_warmup_compiles": result["compiles"]["post_warmup"],
        "threshold": threshold,
    }
    bad = [r for r in result["configs"] if not r["ok"]]
    if bad or result["compiles"]["post_warmup"]:
        out.update(ok=False, decided_by="invariants",
                   error=f"smoke invariants violated: {bad or 'recompiles'}")
        return out
    if result["goodput"]["fraction"] < 0.02:
        out.update(ok=False, decided_by="goodput_floor",
                   error=f"goodput fraction "
                         f"{result['goodput']['fraction']} < 0.02")
        return out
    committed = committed_goodput_reference()
    if committed is not None:
        ref = {r["config"]: r for r in committed.get("configs", [])}
        for row in result["configs"]:
            want = ref.get(row["config"], {}).get("analytic_bytes")
            if want is not None and int(want) != int(row["analytic_bytes"]):
                out.update(
                    ok=False, decided_by="ledger_trajectory",
                    error=(
                        f"{row['config']}: analytic ledger "
                        f"{row['analytic_bytes']} != committed {want} — "
                        "formula/state-layout drift; update "
                        "docs/memory_goodput_cpu.json deliberately"
                    ),
                )
                return out
        out["decided_by"] = "trajectory"
    else:
        out["decided_by"] = "invariants"
        out["note"] = "no committed artifact; invariants only"
    out["ok"] = True
    return out


def committed_elastic_reference(repo: str = REPO):
    """The committed elastic chaos artifact
    (docs/elastic_chaos_cpu.json), or None."""
    path = os.path.join(repo, "docs", "elastic_chaos_cpu.json")
    try:
        return json.load(open(path))
    except (OSError, ValueError):
        return None


def gate_elastic(threshold: float, backend: str, fp: str) -> dict:
    """The elastic-training chaos gate (ROADMAP #1): re-runs
    ``scripts/elastic_smoke.py`` in a subprocess (its phases need their
    own processes for per-phase virtual device counts) and enforces

    1. **Invariants** (hard): the in-process drain→reshape→continue leg
       finishes with the uninterrupted trajectory, ZERO steps lost, and
       a bit-exact-resumable history; the cross-process hard-kill leg
       recovers with steps-lost bounded by the ``save_every_steps``
       cadence;
    2. **Time-to-recover ratchet**: the hard-kill restart's recovery
       RATE (1 / wall-clock seconds) against the committed
       ``docs/elastic_chaos_cpu.json`` and this machine's calibrated
       baseline — wall-clock recovery on a shared CPU container
       breathes, so the elastic threshold is floored at 0.5 (the
       ratchet catches collapses, not scheduler noise).
    """
    import subprocess

    script = os.path.join(REPO, "scripts", "elastic_smoke.py")
    try:
        proc = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            timeout=500, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "decided_by": "worker",
                "error": "elastic_smoke.py timed out"}
    line = next(
        (ln for ln in proc.stdout.splitlines()
         if ln.startswith("ELASTIC_SMOKE_RESULT ")), None,
    )
    if proc.returncode != 0 or line is None:
        tail = (proc.stdout + proc.stderr).strip().splitlines()[-8:]
        return {"ok": False, "decided_by": "invariants",
                "error": "elastic_smoke failed: " + " | ".join(tail)}
    result = json.loads(line[len("ELASTIC_SMOKE_RESULT "):])
    ip, rs = result["in_process"], result.get("restart", {})
    out = {
        "trajectory_equal": ip["trajectory_equal"],
        "bit_exact_resumable": ip["bit_exact_resumable"],
        "steps_lost_clean_drain": ip["steps_lost"],
        "reshape_downtime_secs": ip["reshape_downtime_secs"],
        "steps_lost_hard_kill": rs.get("steps_lost"),
        "steps_lost_bound": rs.get("steps_lost_bound"),
        "time_to_recover_secs": rs.get("time_to_recover_secs"),
        "threshold": threshold,
    }
    if not result["ok"] or not ip["trajectory_equal"] or (
        not ip["bit_exact_resumable"] or ip["steps_lost"] != 0
    ):
        out.update(ok=False, decided_by="invariants",
                   error=f"elastic invariants violated: {result}")
        return out
    if rs and rs["steps_lost"] > rs["steps_lost_bound"]:
        out.update(
            ok=False, decided_by="steps_lost_bound",
            error=f"hard-kill lost {rs['steps_lost']} steps "
            f"(> cadence bound {rs['steps_lost_bound']})",
        )
        return out
    recover_secs = float(rs.get("time_to_recover_secs") or 0.0)
    if recover_secs <= 0:
        out.update(ok=True, decided_by="invariants",
                   note="no restart timing; invariants only")
        return out
    committed = committed_elastic_reference()
    committed_rate = None
    if committed and committed.get("time_to_recover_secs"):
        committed_rate = 1.0 / float(committed["time_to_recover_secs"])
    elastic_key = f"{backend}_elastic"
    baseline = load_baseline(elastic_key, fp)
    decision = evaluate(
        1.0 / recover_secs, committed_rate, baseline,
        max(threshold, 0.5),
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(
            elastic_key, fp, max(1.0 / recover_secs, baseline or 0.0)
        )
    else:
        out["error"] = (
            f"time-to-recover {recover_secs}s regressed "
            f">{max(threshold, 0.5) * 100:.0f}% vs recovery-rate "
            f"baseline {baseline}"
        )
    return out


def committed_kernels_reference(repo: str = REPO):
    """Kernel-engine decode steps/s from the committed kernel-pass
    artifact (docs/kernels_cpu.json), or None."""
    path = os.path.join(repo, "docs", "kernels_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    value = (data.get("decode") or {}).get("decode_steps_per_sec")
    if not isinstance(value, (int, float)):
        return None
    return float(value), data


def gate_kernels(threshold: float, backend: str, fp: str) -> dict:
    """The ops/kernels/ Pallas-pass regression gate: a fresh run of the
    kernel microbench + real-engine decode comparison, gated —

    1. **Invariants** (hard): interpret-mode parity bit-for-bit for all
       three kernels (paged attention, fused Adam tail, int8 matmul),
       engine byte identity gather-vs-``paged_kernel`` across ragged
       traffic, and zero post-warmup compiles in the steady compiled
       decode loop.
    2. **Ratio floor** (machine-independent): the paged_kernel decode
       step holds >= 0.5x the gather engine's step rate — off-TPU both
       dispatch the same reference program, so a real drop means the
       kernel path grew work the gather path does not have.
    3. **Trajectory/local baseline** on the kernel-engine decode
       steps/s, with the calibrate-then-ratchet fallback the parity
       gate uses (machine baseline key ``cpu_kernels``).
    """
    import bench

    result = bench.bench_kernels()
    kernels = result.get("kernels") or {}
    decode = result.get("decode") or {}
    out = {
        "decode_steps_per_sec": decode.get("decode_steps_per_sec"),
        "kernel_vs_gather": decode.get("kernel_vs_gather"),
        "kernel_speedups": {
            name: row.get("speedup") for name, row in kernels.items()
        },
        "threshold": threshold,
    }
    parity_fails = [
        name for name, row in kernels.items()
        if not (row.get("interpret_parity")
                or row.get("trajectory_parity"))
    ]
    if len(kernels) < 3 or parity_fails:
        out.update(ok=False, decided_by="parity",
                   error="interpret-mode parity broken for: "
                   + (", ".join(parity_fails) or "missing kernel rows"))
        return out
    if not decode.get("byte_identical"):
        out.update(ok=False, decided_by="identity",
                   error="paged_kernel engine output diverged from the "
                   "gather+flash engine")
        return out
    if decode.get("post_warmup_compiles") != 0:
        out.update(ok=False, decided_by="zero_recompile",
                   error=f"{decode.get('post_warmup_compiles')} "
                   "compile(s) after warmup in the steady decode loop")
        return out
    ratio = float(decode.get("kernel_vs_gather") or 0.0)
    if ratio < 0.5:
        out.update(
            ok=False, decided_by="ratio_floor",
            error=f"paged_kernel decode step is {ratio}x the gather "
            "engine's rate — below the 0.5x floor (same reference "
            "program off-TPU; the kernel path grew extra work)",
        )
        return out
    committed = committed_kernels_reference()
    kern_key = f"{backend}_kernels"
    baseline = load_baseline(kern_key, fp)
    fresh = float(decode.get("decode_steps_per_sec") or 0.0)
    decision = evaluate(
        fresh, committed[0] if committed else None, baseline, threshold,
    )
    out.update(ok=decision["ok"], decided_by=decision["decided_by"])
    if decision.get("note"):
        out["note"] = decision["note"]
    if decision["ok"]:
        save_baseline(kern_key, fp, max(fresh, baseline or 0.0))
    elif "error" not in out:
        out["error"] = (
            f"kernel-engine decode {fresh} steps/s is "
            f">{threshold * 100:.0f}% below this machine's baseline "
            f"{baseline}"
        )
    return out


def committed_lint_baseline(repo: str = REPO):
    """The committed graft-lint baseline artifact, or None."""
    path = os.path.join(repo, "docs", "graft_lint_baseline.json")
    try:
        return json.load(open(path))
    except (OSError, ValueError):
        return None


def gate_lint() -> dict:
    """The static-analysis gate (graft-lint): re-runs
    ``scripts/graft_lint.py`` in a subprocess (it forces its own
    2-virtual-device process for the pipeline trace) and enforces

    1. **Invariants** (hard): every program traces (a trace failure IS
       a host-sync/contract finding) and the AST pack parses the tree;
    2. **Findings-vs-baseline** (hard): any finding not in the committed
       ``docs/graft_lint_baseline.json`` fails — the clean tree stays
       clean, and accepting a new finding is a deliberate
       ``--update-baseline`` artifact diff, never silent drift.
    """
    import subprocess
    import tempfile

    script = os.path.join(REPO, "scripts", "graft_lint.py")
    out_json = os.path.join(
        tempfile.mkdtemp(prefix="graft_lint_gate_"), "report.json"
    )
    try:
        proc = subprocess.run(
            [sys.executable, script, "--json", out_json],
            capture_output=True, text=True, timeout=280,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "decided_by": "worker",
                "error": "graft_lint.py timed out"}
    try:
        report = json.load(open(out_json))
    except (OSError, ValueError):
        tail = (proc.stdout + proc.stderr).strip().splitlines()[-8:]
        return {"ok": False, "decided_by": "worker",
                "error": "graft_lint produced no report: "
                + " | ".join(tail)}
    diff = report.get("baseline") or {}
    out = {
        "programs_traced": len(report.get("programs_traced") or []),
        "findings": (report.get("counts") or {}).get("total", -1),
        "new_findings": len(diff.get("new") or []),
        "baseline_fingerprint": diff.get("baseline_fingerprint"),
    }
    baseline = committed_lint_baseline()
    if baseline is None:
        out.update(ok=False, decided_by="baseline_missing",
                   error="docs/graft_lint_baseline.json is missing — "
                   "regenerate with scripts/graft_lint.py "
                   "--update-baseline on a clean tree")
        return out
    if proc.returncode != 0 or diff.get("ok") is not True:
        new = diff.get("new") or []
        out.update(
            ok=False, decided_by="findings_vs_baseline",
            error=f"{len(new)} new graft-lint finding(s): "
            + "; ".join(
                f"{f['rule']} @ {f['location']}" for f in new[:6]
            ),
        )
        return out
    out.update(ok=True, decided_by="findings_vs_baseline")
    if diff.get("fixed"):
        out["note"] = (
            f"{len(diff['fixed'])} baseline finding(s) fixed — refresh "
            "the baseline artifact when intentional"
        )
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threshold", type=float, default=float(
        os.environ.get("ML_TRAINER_TPU_BENCH_GATE_THRESHOLD", "0.10")
    ), help="max allowed fractional regression (default 0.10)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--reps", type=int, default=2,
                        help="measurement passes; best rate is compared "
                        "(the standard noise-floor trick)")
    parser.add_argument("--skip-serve", action="store_true",
                        help="skip the paged-serving replay gate (train "
                        "parity gate only)")
    parser.add_argument("--skip-mixed", action="store_true",
                        help="skip the mixed-precision / sharded-update "
                        "gate")
    parser.add_argument("--skip-pipeline", action="store_true",
                        help="skip the pipeline-schedule gate")
    parser.add_argument("--skip-slo", action="store_true",
                        help="skip the serving-SLO open-loop gate")
    parser.add_argument("--skip-disagg", action="store_true",
                        help="skip the disaggregated-serving router gate")
    parser.add_argument("--skip-lora", action="store_true",
                        help="skip the batched-LoRA serving gate "
                        "(identity/zero-recompile/residency/hot-load "
                        "invariants + 0.8x single-model floor + busy "
                        "tokens/s ratchet vs docs/serving_lora_cpu.json)")
    parser.add_argument("--skip-overload", action="store_true",
                        help="skip the serving-chaos overload gate "
                        "(autoscaler + hedging + ladder vs baseline)")
    parser.add_argument("--skip-goodput", action="store_true",
                        help="skip the memory-ledger / goodput / "
                        "recompile gate")
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the graft-lint static-analysis gate")
    parser.add_argument("--skip-kernels", action="store_true",
                        help="skip the ops/kernels/ Pallas-pass gate "
                        "(interpret parity + engine byte identity + "
                        "zero-recompile invariants, decode steps/s "
                        "ratchet vs docs/kernels_cpu.json)")
    parser.add_argument("--skip-elastic", action="store_true",
                        help="skip the elastic-training chaos gate")
    parser.add_argument("--skip-fleet", action="store_true",
                        help="skip the multi-process serving-fleet gate")
    parser.add_argument("--skip-deploy", action="store_true",
                        help="skip the live-rollout (canary deploy + "
                        "SLO-burn auto-rollback) gate")
    parser.add_argument("--skip-watchtower", action="store_true",
                        help="skip the watchtower TSDB/alert-engine gate "
                        "(detection-latency invariant, registry-sweep "
                        "ratchet vs docs/watchtower_cpu.json)")
    parser.add_argument("--changed-only", action="store_true",
                        help="map the files changed vs --changed-ref to "
                        "gate legs (legs_for_changes) and run only "
                        "those — docs-only diffs gate nothing, a "
                        "serving/ diff runs everything; when git cannot "
                        "answer, every leg runs")
    parser.add_argument("--changed-ref", default="origin/main",
                        metavar="REF",
                        help="git ref --changed-only diffs against "
                        "(default origin/main)")
    args = parser.parse_args()

    selected = set(ALL_LEGS)
    if args.changed_only:
        files = changed_files(args.changed_ref)
        selected = legs_for_changes(files)
        print(json.dumps({"bench_gate_changed_only": {
            "ref": args.changed_ref,
            "n_files": len(files) if files is not None else None,
            "legs": sorted(selected),
        }}), flush=True)
        if not selected:
            print("BENCH_GATE OK (changed_only): no gate legs "
                  "selected by the diff", flush=True)
            return 0

    import jax

    jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    fp = machine_fingerprint()

    import bench  # the committed rows were measured through this module

    if "parity" in selected:
        ref = reference_for(backend)
        baseline = load_baseline(backend, fp)
        fresh = 0.0
        for _ in range(max(args.reps, 1)):
            fresh = max(fresh, bench.bench_parity(args.batch_size))

        result = evaluate(
            fresh, float(ref[1]["value"]) if ref else None, baseline,
            args.threshold,
        )
        result.update({
            "backend": backend,
            "reference_round": ref[0] if ref else None,
            "batch_size": args.batch_size,
            "machine": fp,
        })
        if result["ok"]:
            # Ratchet: remember the best this machine has ever shown.
            save_baseline(backend, fp, max(fresh, baseline or 0.0))
        print(json.dumps({"bench_gate": result}), flush=True)
        if not result["ok"]:
            print(
                f"BENCH_GATE FAIL: {result['fresh_samples_per_sec']} "
                f"samples/s is >{args.threshold * 100:.0f}% below this "
                f"machine's baseline {result['local_baseline']} "
                "samples/s",
                flush=True,
            )
            return 1
        print(
            f"BENCH_GATE OK ({result['decided_by']}): "
            f"{result['fresh_samples_per_sec']} samples/s",
            flush=True,
        )
    if not args.skip_serve and "serve" in selected:
        serve = gate_serve_replay(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_serve": serve}), flush=True)
        if not serve["ok"]:
            print(f"BENCH_GATE SERVE FAIL: {serve.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE SERVE OK ({serve['decided_by']}): paged "
            f"{serve['paged_tokens_per_sec']} tokens/s "
            f"({serve['speedup']}x contiguous, TTFT p99 ratio "
            f"{serve['ttft_p99_ratio']})",
            flush=True,
        )
    if not args.skip_mixed and "mixed" in selected:
        mixed = gate_mixed(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_mixed": mixed}), flush=True)
        if not mixed["ok"]:
            print(f"BENCH_GATE MIXED FAIL: {mixed.get('error')}", flush=True)
            return 1
        print(
            f"BENCH_GATE MIXED OK ({mixed['decided_by']}): sharded update "
            f"{mixed['sharded_vs_fused_fp32']}x fused at fp32, "
            f"{mixed['sharded_vs_fused_bf16']}x at bf16",
            flush=True,
        )
    if not args.skip_pipeline and "pipeline" in selected:
        pipe = gate_pipeline(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_pipeline": pipe}), flush=True)
        if not pipe["ok"]:
            print(f"BENCH_GATE PIPELINE FAIL: {pipe.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE PIPELINE OK ({pipe['decided_by']}): 1f1b at "
            f"{pipe['gpipe_over_1f1b_s4_m8']}x gpipe step rate "
            f"(S=4/M=8), {pipe.get('f1b_steps_per_sec')} steps/s",
            flush=True,
        )
    if not args.skip_slo and "slo" in selected:
        slo = gate_slo(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_slo": slo}), flush=True)
        if not slo["ok"]:
            print(f"BENCH_GATE SLO FAIL: {slo.get('error')}", flush=True)
            return 1
        print(
            f"BENCH_GATE SLO OK ({slo['decided_by']}): "
            f"{slo['tokens_per_sec']} tokens/s at {slo['offered_rps']} "
            f"rps, TTFT p99 {slo['ttft_p99_ms']} ms, attainment "
            f"{slo['attainment']}",
            flush=True,
        )
    if not args.skip_disagg and "disagg" in selected:
        disagg = gate_disagg(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_disagg": disagg}), flush=True)
        if not disagg["ok"]:
            print(f"BENCH_GATE DISAGG FAIL: {disagg.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE DISAGG OK ({disagg['decided_by']}): "
            f"disaggregated {disagg['disagg_tokens_per_sec']} tokens/s, "
            f"TTFT p99 ratio {disagg['ttft_p99_ratio']} vs colocated, "
            f"{disagg['migrations']} migration(s)",
            flush=True,
        )
    if not args.skip_fleet and "fleet" in selected:
        fleet = gate_fleet(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_fleet": fleet}), flush=True)
        if not fleet["ok"]:
            print(f"BENCH_GATE FLEET FAIL: {fleet.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE FLEET OK ({fleet['decided_by']}): "
            f"{fleet['fleet_tokens_per_sec']} tokens/s across worker "
            f"processes, chunked TTFT ratio "
            f"{fleet['chunked_ttft_ratio']}, "
            f"{fleet['migrations']} socket migration(s), respawned pid "
            f"{fleet['respawned_pid']}",
            flush=True,
        )
    if not args.skip_deploy and "deploy" in selected:
        dep = gate_deploy(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_deploy": dep}), flush=True)
        if not dep["ok"]:
            print(f"BENCH_GATE DEPLOY FAIL: {dep.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE DEPLOY OK ({dep['decided_by']}): mid-load "
            f"deploy {dep['deploy_state']} in {dep['deploy_s']}s, "
            f"forced regression {dep['rollback_state']} "
            f"{dep['rollback_s']}s after first high burn, "
            f"{dep['final_tokens_per_sec']} tokens/s post-rollback",
            flush=True,
        )
    if not args.skip_lora and "lora" in selected:
        lo = gate_lora(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_lora": lo}), flush=True)
        if not lo["ok"]:
            print(f"BENCH_GATE LORA FAIL: {lo.get('error')}", flush=True)
            return 1
        print(
            f"BENCH_GATE LORA OK ({lo['decided_by']}): "
            f"{lo['adapters_resident']} adapters at "
            f"{lo['lora_tokens_per_sec_busy']} busy tokens/s "
            f"({lo['tokens_per_sec_ratio']}x single-model), hot-load "
            f"{lo['hot_load_tokens']} token(s)",
            flush=True,
        )
    if not args.skip_overload and "overload" in selected:
        ov = gate_overload(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_overload": ov}), flush=True)
        if not ov["ok"]:
            print(f"BENCH_GATE OVERLOAD FAIL: {ov.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE OVERLOAD OK ({ov['decided_by']}): chaos "
            f"attainment {ov['mitigated_attainment']} mitigated vs "
            f"{ov['baseline_attainment']} baseline "
            f"({ov['attainment_ratio']}x), {ov['hedges']} hedge(s), "
            f"autoscaler {ov['autoscaler_actions']}",
            flush=True,
        )
    if not args.skip_goodput and "goodput" in selected:
        gp = gate_goodput(args.threshold)
        print(json.dumps({"bench_gate_goodput": gp}), flush=True)
        if not gp["ok"]:
            print(f"BENCH_GATE GOODPUT FAIL: {gp.get('error')}", flush=True)
            return 1
        print(
            f"BENCH_GATE GOODPUT OK ({gp['decided_by']}): "
            f"{len(gp['configs'])} ledger configs agree, goodput "
            f"{gp['goodput_fraction']}, "
            f"{gp['post_warmup_compiles']} post-warmup compiles",
            flush=True,
        )
    if not args.skip_watchtower and "watchtower" in selected:
        wt = gate_watchtower(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_watchtower": wt}), flush=True)
        if not wt["ok"]:
            print(f"BENCH_GATE WATCHTOWER FAIL: {wt.get('error')}",
                  flush=True)
            if wt.get("attribution"):
                print(wt["attribution"], flush=True)
            return 1
        print(
            f"BENCH_GATE WATCHTOWER OK ({wt['decided_by']}): "
            f"{wt['sample_ops_per_sec']} registry sweeps/s over "
            f"{wt['series']} series, alert eval "
            f"{wt['alert_eval_mean_ms']}ms, regression fired on first "
            "eval",
            flush=True,
        )
    if not args.skip_elastic and "elastic" in selected:
        ela = gate_elastic(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_elastic": ela}), flush=True)
        if not ela["ok"]:
            print(f"BENCH_GATE ELASTIC FAIL: {ela.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE ELASTIC OK ({ela['decided_by']}): reshape "
            f"downtime {ela['reshape_downtime_secs']}s, hard-kill lost "
            f"{ela['steps_lost_hard_kill']} step(s) (bound "
            f"{ela['steps_lost_bound']}), recovered in "
            f"{ela['time_to_recover_secs']}s",
            flush=True,
        )
    if not args.skip_kernels and "kernels" in selected:
        kern = gate_kernels(args.threshold, backend, fp)
        print(json.dumps({"bench_gate_kernels": kern}), flush=True)
        if not kern["ok"]:
            print(f"BENCH_GATE KERNELS FAIL: {kern.get('error')}",
                  flush=True)
            return 1
        print(
            f"BENCH_GATE KERNELS OK ({kern['decided_by']}): "
            f"{kern['decode_steps_per_sec']} decode steps/s "
            f"({kern['kernel_vs_gather']}x gather engine), parity + "
            "identity + zero-recompile pinned",
            flush=True,
        )
    if not args.skip_lint and "lint" in selected:
        lint = gate_lint()
        print(json.dumps({"bench_gate_lint": lint}), flush=True)
        if not lint["ok"]:
            print(f"BENCH_GATE LINT FAIL: {lint.get('error')}", flush=True)
            return 1
        print(
            f"BENCH_GATE LINT OK ({lint['decided_by']}): "
            f"{lint['programs_traced']} programs traced, "
            f"{lint['findings']} finding(s), 0 new vs baseline "
            f"{lint['baseline_fingerprint']}",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
