#!/usr/bin/env python
"""Fleet observability-plane smoke leg (scripts/fastlane.sh) — the
PR 19 tentpole end to end, with REAL OS processes (serving/fleet.py +
the router's fleet plane in serving/router.py):

1. **Metrics federation** — a 3-process fleet (1 prefill + 2 decode)
   serves a seeded open-loop trace byte-identical to in-driver
   ``generate()`` with zero post-warmup compiles per worker process,
   WHILE the router scrapes every worker's ``/metrics`` and re-exports
   the union on its own ``/metrics``: every worker series carries
   ``replica=``/``role=``/``generation=`` labels, each worker's
   ``compile_events_post_warmup_total`` is present (at 0), a re-scrape
   is byte-identical on the worker sections (no histogram
   double-count), the aggregated ``/healthz`` names each replica's
   post-warmup compile count and degradation level, and every loadgen
   row names the replica that served it.
2. **Cross-process tracing** — ``Router.save_fleet_trace`` merges
   ``GET /trace`` from every worker into ONE clock-aligned Perfetto
   timeline: >= 2 process lanes, and a migrated request whose
   prefill-side fragment (prefill worker's lane) ends before its
   decode-side span (a DIFFERENT pid's lane) begins.
3. **Incident bundles** — a real ``SIGKILL`` of a decode worker: the
   router's poller notices the death and assembles an
   ``incident_<ts>/`` bundle containing the router's own flight dump,
   every SURVIVING replica's flight dump, the federated metrics
   snapshot, SLO timelines, and the dead worker's stderr tail; the
   scrape-error counter for the dead replica ticks instead of the
   poller crashing.

Prints ``FLEET_OBS_SMOKE OK`` / ``FLEET_OBS_SMOKE FAIL: <why>``;
non-zero exit on any violation.  CPU-only, 3 worker processes, tiny
model.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"FLEET_OBS_SMOKE FAIL: {msg}")
    return 1


def worker_lines(text: str):
    """Federated sample lines carrying a replica= label (the worker
    sections; router-own series have none)."""
    return [
        ln for ln in text.splitlines()
        if ln and not ln.startswith("#") and 'replica="' in ln
    ]


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    rows = [
        ScheduledRequest(
            arrival_s=i * 0.02, tenant=f"tenant{i % 2}",
            prompt=rng.integers(
                0, model.vocab_size, int(rng.integers(8, 25))
            ).astype(np.int32),
            max_new_tokens=8,
        )
        for i in range(8)
    ]
    trace = schedule_from_trace(schedule_to_records(rows))
    refs = [
        [int(t) for t in np.asarray(
            generate(model, variables, s.prompt[None], s.max_new_tokens)
        )[0]]
        for s in trace
    ]

    fleet = Fleet(
        roles=["prefill", "decode", "decode"],
        model_name="gpt2_tiny", max_len=64, max_batch=2,
        kv_page_size=8, prefill_chunk=16, seed=0,
    )
    fleet.start()
    incident_root = tempfile.mkdtemp(prefix="fleet-obs-smoke-")
    router = fleet.make_router(
        hedging=False, metrics_scrape_interval=0.1,
        incident_dir=incident_root, incident_min_interval_s=0.0,
    )
    workers = sorted(fleet.replicas)
    try:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"

        # -- leg 1: federation under live traffic ----------------------
        for _ in range(2):  # untimed: workers compile to steady state
            run_open_loop(trace, url=url, time_scale=0.0)

        def compiles():
            return {
                n: int(r._get("/v1/spec")["compiles"] or 0)
                for n, r in fleet.replicas.items()
            }

        before = compiles()
        client = run_open_loop(trace, url=url, collect_tokens=True)
        after = compiles()
        if client["n_errors"]:
            return fail(f"{client['n_errors']} client error(s)")
        for r, ref in zip(client["per_request"], refs):
            if r.get("output") != ref:
                return fail(
                    "fleet output diverged from generate() with the "
                    "observability plane enabled"
                )
        fresh = {n: after[n] - before[n] for n in after}
        if any(fresh.values()):
            return fail(f"post-warmup worker recompiles: {fresh}")
        no_replica = [
            i for i, r in enumerate(client["per_request"])
            if not r.get("replica")
        ]
        if no_replica:
            return fail(f"loadgen rows missing replica id: {no_replica}")

        router.scrape_metrics(force=True)
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            fed = resp.read().decode()
        lines = worker_lines(fed)
        for name in workers:
            rep = fleet.replicas[name]
            want = (
                f'replica="{name}"', f'role="{rep.role}"', 'generation="'
            )
            if not any(
                ln.startswith("compile_events_post_warmup_total{")
                and all(w in ln for w in want)
                for ln in lines
            ):
                return fail(
                    f"federated exposition missing {name}'s labelled "
                    "compile_events_post_warmup_total"
                )
        router.scrape_metrics(force=True)
        if worker_lines(router.federated_metrics_text()) != lines:
            return fail(
                "re-scrape changed the federated worker sections "
                "(snapshots must replace, never accumulate)"
            )
        with urllib.request.urlopen(f"{url}/healthz", timeout=10) as resp:
            hz = json.loads(resp.read())
        for name in workers:
            h = hz.get("replicas", {}).get(name, {})
            for key in ("compile_events_post_warmup_total",
                        "degradation_level"):
                if key not in h:
                    return fail(
                        f"aggregated /healthz [{name}] missing {key}"
                    )
        print(
            f"# fleet obs smoke: {len(trace)} requests byte-identical "
            f"across 3 processes with the plane on, {len(lines)} "
            "federated worker lines, idempotent re-scrape, replica ids "
            "on every loadgen row"
        )

        # -- leg 2: one clock-aligned fleet trace ----------------------
        trace_path = os.path.join(incident_root, "fleet_trace.json")
        router.save_fleet_trace(trace_path)
        with open(trace_path, encoding="utf-8") as fp:
            merged = json.load(fp)
        events = merged.get("traceEvents", [])
        lanes = {e.get("pid") for e in events if e.get("ph") != "M"}
        if len(lanes) < 2:
            return fail(f"merged trace holds {len(lanes)} lane(s)")
        causal = None
        router_pid = os.getpid()  # the router's lane: its own request
        for ev in events:         # spans start at submit, pre-prefill
            name = ev.get("name", "")
            if not name.startswith("kv_wire "):
                continue
            tid = name.split(" ", 1)[1]
            pre = next(
                (e for e in events
                 if e.get("name") == f"request {tid} (prefill)"), None,
            )
            dec = next(
                (e for e in events
                 if e.get("name") == f"request {tid}"
                 and e.get("pid") not in (
                     (pre or {}).get("pid"), router_pid,
                 )), None,
            )
            if pre is None or dec is None:
                continue
            # Epoch alignment is exact on one host; allow the NTP
            # fallback's rtt/2 error bound.
            if dec["ts"] >= pre["ts"] + pre.get("dur", 0.0) - 5_000.0:
                causal = (tid, pre["pid"], dec["pid"])
                break
        if causal is None:
            return fail(
                "no migrated request spans two process lanes in causal "
                "order on the merged timeline"
            )
        print(
            f"# fleet obs smoke: merged trace {len(events)} events / "
            f"{len(lanes)} lanes, request {causal[0]} prefill@pid "
            f"{causal[1]} -> decode@pid {causal[2]} in causal order"
        )

        # -- leg 3: SIGKILL -> incident bundle -------------------------
        victim = fleet.replicas["decode0"]
        fleet.kill("decode0")  # SIGKILL, no goodbye
        deadline = time.monotonic() + 90
        bundle = None
        while time.monotonic() < deadline:
            bundle = router.last_incident_path
            if bundle and os.path.exists(
                os.path.join(bundle, "manifest.json")
            ):
                break
            time.sleep(0.1)
        else:
            return fail(
                "router never assembled an incident bundle after the "
                "SIGKILL"
            )
        have = set(os.listdir(bundle))
        want = {"flight_router.json", "metrics.prom", "router.json",
                "slo_timelines.json", "manifest.json",
                "stderr_decode0.txt"}
        want |= {
            f"flight_{n}.json" for n in workers if n != "decode0"
        }
        missing = want - have
        if missing:
            return fail(f"incident bundle missing {sorted(missing)}")
        with open(os.path.join(bundle, "manifest.json"),
                  encoding="utf-8") as fp:
            manifest = json.load(fp)
        if "decode0" not in manifest.get("dead", []):
            return fail(f"manifest does not name the dead worker: "
                        f"{manifest.get('dead')}")
        # The dead replica's scrape must tick the error counter, not
        # crash the poller.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.scrape_metrics(force=True)
            snap = router.snapshot()
            if snap.get("scrape_errors_total", {}).get(
                "decode0", 0
            ) >= 1:
                break
            time.sleep(0.1)
        else:
            return fail(
                "dead replica never bumped "
                "router_replica_scrape_errors_total"
            )
        print(
            f"# fleet obs smoke: SIGKILL pid {victim.pid} -> bundle "
            f"{os.path.basename(bundle)} with {len(have)} artifact(s) "
            "incl. surviving flight dumps + dead stderr tail"
        )
    finally:
        router.close()
        fleet.stop()
    print("FLEET_OBS_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
