#!/bin/bash
# Resume-aware TPU session: run ONLY the runbook stages whose artifacts
# are still missing.  A wedged tunnel mid-session (2026-07-30: one session
# delivered the headline + ResNet-50 rows, then hung every later stage)
# costs only the stages it interrupted — re-runs pick up from there.
# Safe to re-run any number of times.
#
#   tpu_recover.sh          run the missing stages (probes the TPU first)
#   tpu_recover.sh --check  exit 0 iff every stage would skip (no device
#                           touch; the watcher's completeness test)
#
# A stage that hits its timeout aborts the whole pass (exit 2): on this
# tunnel a timeout means the session is wedged, and every later stage
# would burn its full timeout against a dead chip.  The watcher re-probes
# and retries on the next cycle.
#
# The driver-facing `python bench.py` / `--extended` paths (single
# parseable JSON record incl. TIMEOUT rows) are unchanged — this script
# is the artifact-recovery path, not the driver contract.
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
# Artifacts land IN the repo (not /tmp): perf evidence must survive the
# session (VERDICT r4 missing #2) — the driver commits any uncommitted
# work at round end, so even a wedge mid-pass loses nothing.  The round
# directory is derived from the newest driver record (BENCH_r0N is
# written at the END of round N, so the round in flight is N+1) — no
# hand-bump each round, no cross-round commingling.
LAST_ROUND=$(ls BENCH_r*.json 2>/dev/null | sed 's/[^0-9]*//g' \
  | sort -n | tail -1)
OUT=$(printf 'docs/bench_sessions/r%02d' $(( 10#${LAST_ROUND:-0} + 1 )))
# Host-wide tunnel mutex shared with bench.py / bench_decode.py
# (ml_trainer_tpu/utils/tunnel.py) and tpu_watch.sh: concurrent dials
# are the leading wedge suspect.  Each stage takes it for its own
# duration only, so a driver-launched bench.py interleaves after at most
# one stage.  LOCKRUN writes the holder sidecar so waiting clients can
# attribute contention, and maps lock-wait timeout to rc 75
# (EX_TEMPFAIL) — distinguishable from a real stage failure.
LOCK=/tmp/tpu_tunnel.lock
LOCKRUN() { # LOCKRUN <flock-wait-secs> <label> <cmd...>
  local wait_secs=$1 label=$2; shift 2
  flock -w "$wait_secs" -E 75 "$LOCK" \
    env TPU_TUNNEL_LOCK_HELD=1 bash -c '
      echo "pid=$$ $0 $(date -u +%H:%M:%SZ)" > /tmp/tpu_tunnel.holder
      exec "$@"' "$label" "$@"
}
mkdir -p "$OUT" tests/golden

# --- skip conditions, one function per stage -------------------------------
# The headline is done iff the reconcile record holds BOTH dispatch paths:
# bench.py writes per_batch_samples_per_sec into the record before the
# multi-step pass (deliberately, so a hang cannot lose it), so that key
# alone does NOT mean the session finished — require a numeric "value"
# (only set after the multi-step pass) too.  A "note" key marks a
# CPU-fallback or CPU-pinned record (bench.py sets it in exactly those
# cases) — those numbers must not stand in for the TPU headline.
headline_done() {
  grep -q '"per_batch_samples_per_sec"' "$OUT/bench_headline.out" 2>/dev/null \
    && grep -q '"value": [0-9]' "$OUT/bench_headline.out" \
    && ! grep -q '"note"' "$OUT/bench_headline.out"
}
loaders_done() {
  grep -q 'input pipeline native' "$OUT/loaders.out" 2>/dev/null
}
# Row-anchored ([^}]* cannot cross the row's closing brace, so a later
# model's keys cannot vouch for an earlier TIMEOUT row in the single-line
# --extended record) and TPU-proven: a numeric "mfu" (old-format rows) or
# an explicit "backend": "tpu" (rows since the backend key was added —
# mfu alone is not enough, it is legitimately null when XLA cost analysis
# is unavailable, and absent on CPU-fallback rows).
model_done() {
  grep -hqE "\"model\": \"$1\", \"batch_shape\": [^}]*(\"mfu\": [0-9]|\"backend\": \"tpu\")" \
    "$OUT"/bench_extended.out "$OUT"/one_*.out 2>/dev/null
}
# ResNet-50 at larger batch (the MFU ledger, VERDICT r3 #2): rows keyed
# by their batch_shape so bs=32 cannot vouch for bs=128/256.
r50_batch_done() {
  grep -hqE "\"model\": \"resnet50\", \"batch_shape\": \[$1, [^}]*\"backend\": \"tpu\"" \
    "$OUT"/one_resnet50_b$1.out 2>/dev/null
}
tune_done() {
  python - <<'EOF' 2>/dev/null
import json, sys
rec = json.load(open("docs/flash_block_tune.json"))
sys.exit(0 if rec.get("best") and "TPU" in rec.get("device", "") else 1)
EOF
}
ledger_done() {
  python - <<'EOF' 2>/dev/null
import json, sys
rec = json.load(open("docs/resnet50_mfu_ledger.json"))
rows = rec.get("rows", [])
ok = {r.get("batch") for r in rows if r.get("backend") == "tpu"}
sys.exit(0 if {32, 128, 256} <= ok else 1)
EOF
}
decode_done() {
  python - <<'EOF' 2>/dev/null
import json, sys
rec = json.load(open("docs/decode_bench.json"))
models = {r.get("model") for r in rec.get("rows", [])}
sys.exit(0 if rec.get("backend") == "tpu" and {"gpt2", "llama"} <= models
         else 1)
EOF
}
golden_done() {
  python - <<'EOF' 2>/dev/null
import json, sys
rec = json.load(open("tests/golden/local_run_tpu.json"))
sys.exit(0 if rec.get("backend") == "tpu" else 1)
EOF
}
flash_done() {
  python - <<'EOF' 2>/dev/null
import json, sys
rec = json.load(open("docs/flash_tpu_validation.json"))
sys.exit(0 if rec.get("all_pass") and "TPU" in rec.get("device", "") else 1)
EOF
}
# The guard cell's OUTPUT records the device list of the backend that ran
# ('[TPU v5 lite0]' on the chip).  Plain 'TPU' also matches the notebooks'
# own prose ('TPU-native', ...), so anchor on the device-repr prefix.
notebook_done() {
  f=$(ls notebooks/$1_*.ipynb 2>/dev/null | head -1)
  [ -n "$f" ] && grep -q 'TPU v' "$f"
}

if [ "${1:-}" = "--check" ]; then
  headline_done || exit 1
  loaders_done || exit 1
  for m in resnet50 vit_b16 bert_base gpt2; do model_done "$m" || exit 1; done
  for b in 128 256; do r50_batch_done "$b" || exit 1; done
  ledger_done || exit 1
  tune_done || exit 1
  decode_done || exit 1
  golden_done || exit 1
  flash_done || exit 1
  notebook_done 01 || exit 1
  notebook_done 03 || exit 1
  exit 0
fi

# run_stage <secs> <outfile> <cmd...>: run under timeout, tee the tail to
# the console, abort the pass on a stage timeout (wedged tunnel).
run_stage() {
  secs=$1; outfile=$2; shift 2
  # LOCKRUN serializes against other tunnel clients; TPU_TUNNEL_LOCK_HELD
  # tells the child bench.py not to re-acquire (flock is fd-scoped — the
  # child taking a fresh fd on the same path would deadlock against its
  # own parent).  -w 360 outwaits one 240s probe plus slack.
  LOCKRUN 360 "tpu_recover:$outfile" timeout "$secs" "$@" > "$outfile" 2>&1
  rc=$?
  if [ "$rc" -eq 75 ]; then
    echo "tunnel lock held by: $(cat /tmp/tpu_tunnel.holder 2>/dev/null)" \
      >> "$outfile"
    echo "== stage skipped: tunnel lock held by another client — " \
         "aborting pass (the tunnel is in use, not wedged) =="
    exit 3
  fi
  tail -12 "$outfile"
  if [ "$rc" -eq 124 ]; then
    echo "== stage timed out (${secs}s) — tunnel wedged, aborting pass =="
    exit 2
  fi
  # bench.py's in-process watchdog converts a hang into exit(1) + an error
  # JSON (it fires BELOW the shell timeout so the record still lands) —
  # that is the same wedged-tunnel signal as rc 124.
  if grep -q '"error": "watchdog' "$outfile" 2>/dev/null; then
    echo "== stage hit its in-process watchdog — tunnel wedged, aborting pass =="
    exit 2
  fi
  return "$rc"
}

echo "== probe =="
LOCKRUN ${PROBE_LOCK_WAIT:-360} "tpu_recover:probe" timeout 240 python -u -c \
  "import jax; print(jax.devices())"
probe_rc=$?
if [ "$probe_rc" -eq 75 ]; then
  echo "tunnel lock held by $(cat /tmp/tpu_tunnel.holder 2>/dev/null); " \
       "aborting recovery (tunnel in use, not down)"
  exit 3
elif [ "$probe_rc" -ne 0 ]; then
  echo "TPU unavailable; aborting recovery"; exit 1
fi

if headline_done; then
  echo "== 1. headline bench: already recorded, skipping =="
else
  echo "== 1. headline bench (reconcile) =="
  BENCH_WATCHDOG_SECS=1500 \
    run_stage 1700 "$OUT/bench_headline.out" python bench.py --reconcile
fi

if loaders_done; then
  echo "== 1b. loader bench: already recorded, skipping =="
else
  echo "== 1b. host input-pipeline bench (no device work) =="
  run_stage 900 "$OUT/loaders.out" python bench.py --loaders --cpu
fi

for m in resnet50 vit_b16 bert_base gpt2; do
  if model_done "$m"; then
    echo "== 2. $m: already measured, skipping =="
    continue
  fi
  echo "== 2. $m =="
  # --assume-up: this pass's own probe just ran; bench.py's pre-probe
  # would both duplicate the init and convert a wedged-tunnel hang into
  # a swallowed exit 1 instead of the rc-124 timeout that aborts the pass.
  run_stage 600 "$OUT/one_$m.out" python bench.py --one "$m" --assume-up \
    || true
done

for b in 128 256; do
  if r50_batch_done "$b"; then
    echo "== 2b. resnet50 bs=$b: already measured, skipping =="
    continue
  fi
  echo "== 2b. resnet50 bs=$b (MFU ledger) =="
  run_stage 900 "$OUT/one_resnet50_b$b.out" \
    python bench.py --one resnet50 --batch_size "$b" --assume-up || true
done

if ledger_done; then
  echo "== 2c. MFU ledger: already recorded, skipping =="
else
  echo "== 2c. resnet50 MFU roofline ledger =="
  run_stage 1500 "$OUT/ledger.out" python scripts/mfu_ledger.py || true
fi

if tune_done; then
  echo "== 2d. flash block tune: already recorded, skipping =="
else
  echo "== 2d. flash-attention block-size sweep (GPT-2 shape) =="
  run_stage 1200 "$OUT/flash_tune.out" python scripts/flash_tune.py || true
fi

if decode_done; then
  echo "== 2e. decode bench: already recorded, skipping =="
else
  echo "== 2e. decode perf (GPT-2 + llama tokens/s, greedy + beam) =="
  run_stage 1500 "$OUT/decode.out" python scripts/bench_decode.py || true
fi

if golden_done; then
  echo "== 3. golden: TPU record already committed, skipping =="
else
  echo "== 3. golden-run capture =="
  GOLDEN_OUT=tests/golden/local_run_tpu.json MODEL_DIR=/tmp/golden_model \
    run_stage 1800 "$OUT/golden.out" python examples/01_local_training.py
fi

if flash_done; then
  echo "== 4. flash validation: already recorded, skipping =="
else
  echo "== 4. flash-attention TPU validation =="
  run_stage 1800 "$OUT/flash.out" python scripts/validate_flash_tpu.py
fi

for nb in 01 03; do
  if notebook_done "$nb"; then
    echo "== 5. notebook $nb: TPU-executed copy committed, skipping =="
    continue
  fi
  echo "== 5. notebook $nb =="
  MODEL_DIR=model_output \
    run_stage 1800 "$OUT/nb$nb.out" python scripts/make_notebooks.py --only "$nb"
done

echo "== recovery pass done =="
