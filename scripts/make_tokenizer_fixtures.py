"""Generate the committed tokenizer fixtures
(tests/fixtures/tokenizers/): a REAL byte-level BPE vocab trained on a
small embedded corpus (vocab.json + merges.txt, the GPT-2 file format)
and a WordPiece vocab.txt (BERT format).

The fixtures make the tokenizer tests self-contained in this
zero-egress environment: both file formats are exactly what the public
pretrained tokenizers ship, so tests/test_tokenizers.py can pin parity
between the in-tree implementations and ``transformers``' slow
tokenizers loading the SAME files.  Deterministic: ties in the merge
count break lexicographically.

    python scripts/make_tokenizer_fixtures.py [--merges 200]
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from ml_trainer_tpu.data.tokenizers import (  # noqa: E402
    WordPieceTokenizer,
    _byte_encoder,
    pretokenize,
)

CORPUS = """
The quick brown fox jumps over the lazy dog. A framework for training
models on TPU hardware: the trainer compiles one step, shards it over a
device mesh, and streams batches from the input pipeline. Attention is
all you need, but bandwidth is what you pay for. Tokens in, gradients
out; the optimizer updates the parameters and the scheduler decays the
learning rate. It's training time: don't stop until the loss converges,
we're watching the metrics. Checkpoints save every epoch so a failure
costs minutes, not days. Numbers like 123 and 2026 tokenize too, as do
symbols #@! and mixed words like bf16 and v5e. Distributed data
parallel replicates weights; tensor parallel splits them; pipeline
parallel stages them. The cat sat on the mat and the model sat on the
mesh.
"""


def train_bpe(corpus: str, n_merges: int):
    enc = _byte_encoder()
    words = collections.Counter()
    for pre in pretokenize(corpus):
        words["".join(enc[b] for b in pre.encode("utf-8"))] += 1
    # Every word is a tuple of current symbols; merges fuse adjacent pairs.
    splits = {w: tuple(w) for w in words}
    merges = []
    for _ in range(n_merges):
        pairs: collections.Counter = collections.Counter()
        for w, count in words.items():
            parts = splits[w]
            for a, b in zip(parts, parts[1:]):
                pairs[(a, b)] += count
        if not pairs:
            break
        # max() keeps the FIRST maximum, so iterating in sorted order
        # makes the lexicographically-smallest pair win count ties —
        # deterministic output across runs.
        best = max(sorted(pairs), key=lambda p: pairs[p])
        merges.append(best)
        fused = best[0] + best[1]
        new_splits = {}
        for w, parts in splits.items():
            out = []
            k = 0
            while k < len(parts):
                if k + 1 < len(parts) and (parts[k], parts[k + 1]) == best:
                    out.append(fused)
                    k += 2
                else:
                    out.append(parts[k])
                    k += 1
            new_splits[w] = tuple(out)
        splits = new_splits
    # Vocab: the 256 byte symbols in byte order, then merge products.
    vocab = {c: i for i, c in enumerate(
        [enc[b] for b in range(256)] + [a + b for a, b in merges]
    )}
    return vocab, merges


def build_wordpiece_vocab(corpus: str):
    """Specials + every seen char (whole and ## form) + frequent whole
    words + common suffix pieces — enough structure for greedy
    longest-match to produce real multi-piece splits."""
    tmp = WordPieceTokenizer({}, do_lower_case=True)
    words = collections.Counter(tmp._basic_tokens(corpus))
    chars = sorted({c for w in words for c in w})
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    vocab += chars + ["##" + c for c in chars]
    vocab += ["##ing", "##ed", "##er", "##es", "##s", "##ly", "##tion"]
    # Whole words seen at least twice; the rest exercise the piecing path.
    vocab += sorted(w for w, c in words.items() if c >= 2 and len(w) > 1)
    seen = set()
    uniq = [t for t in vocab if not (t in seen or seen.add(t))]
    return uniq


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--merges", type=int, default=200)
    ap.add_argument(
        "--out", default=os.path.join(ROOT, "tests", "fixtures",
                                      "tokenizers")
    )
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    vocab, merges = train_bpe(CORPUS, args.merges)
    with open(os.path.join(args.out, "vocab.json"), "w",
              encoding="utf-8") as fp:
        json.dump(vocab, fp, ensure_ascii=False)
    with open(os.path.join(args.out, "merges.txt"), "w",
              encoding="utf-8") as fp:
        fp.write("#version: 0.2\n")
        for a, b in merges:
            fp.write(f"{a} {b}\n")

    wp = build_wordpiece_vocab(CORPUS)
    with open(os.path.join(args.out, "vocab.txt"), "w",
              encoding="utf-8") as fp:
        fp.write("\n".join(wp) + "\n")

    print(f"BPE: {len(vocab)} tokens, {len(merges)} merges; "
          f"WordPiece: {len(wp)} tokens -> {args.out}")


if __name__ == "__main__":
    main()
