"""ResNet-50 MFU ledger — where the time goes, measured on the chip.

The 19.3% MFU headline row (BASELINE.md) was taken at bs=32 with no
breakdown.  This script measures the full train step at bs=32/128/256
and writes a roofline ledger per batch size:

* achieved FLOP/s vs the chip's bf16 peak (MFU),
* achieved HBM bytes/s vs the chip's peak bandwidth,
* the flops/byte arithmetic intensity of the compiled program,

which together say WHETHER each configuration is MXU-bound or HBM-bound
and how much the MXU fills as the batch grows — the evidence VERDICT r3
item 2 asks for.  Writes docs/resnet50_mfu_ledger.json and prints one
line per row.

Beside the analytic cross-check, the ledger now carries per-kernel
before/after columns from the ``ops/kernels/`` microbench artifact
(``docs/kernels_cpu.json``, regenerated with ``bench.py --kernels``):
reference-vs-fused microseconds and parity per kernel, so the roofline
rows and the kernel-level wins land in one document.  ``--kernels-only``
prints just that table (no chip needed).

    python scripts/mfu_ledger.py [--model resnet50] [--batches 32,128,256]
    python scripts/mfu_ledger.py --kernels-only
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

def chip_peaks():
    """(peak FLOP/s, peak HBM B/s, matched-generation label).

    Both peak tables live in the telemetry spine (telemetry/flops.py) —
    one owner, so the ledger, bench.py, and the trainer's live MFU line
    can never disagree by hardware generation.  The label is recorded in
    the ledger so an unrecognized device kind — which falls back to the
    v5e numbers and can skew the mxu-vs-hbm 'bound' verdict — is visible
    in the artifact instead of silent."""
    from ml_trainer_tpu.telemetry.flops import (
        chip_generation_label,
        chip_peak_flops,
        chip_peak_hbm_bytes,
    )

    return chip_peak_flops(), chip_peak_hbm_bytes(), chip_generation_label()


def measure(model_name: str, batch: int) -> dict:
    import optax

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops import get_criterion, get_optimizer
    from ml_trainer_tpu.train_state import TrainState
    from ml_trainer_tpu.utils.profiler import force

    model = get_model(model_name, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 10, batch), jnp.int32)
    jax.block_until_ready((x, y))
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, x, train=False
    )
    params = variables["params"]
    tx = get_optimizer("adamw", 1e-4)
    criterion = get_criterion("cross_entropy")
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=jax.jit(tx.init)(params),
        batch_stats=variables.get("batch_stats", {}),
        rng=jax.random.PRNGKey(1),
    )

    has_bs = bool(variables.get("batch_stats", {}))

    def step(state, x, y):
        def loss_fn(p):
            if not has_bs:  # ViT/BERT-class: no BatchNorm collection
                out = model.apply({"params": p}, x, train=True)
                return criterion(out, y), state.batch_stats
            out, mut = model.apply(
                {"params": p, "batch_stats": state.batch_stats},
                x, train=True, mutable=["batch_stats"],
            )
            return criterion(out, y), mut["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        return state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=opt_state,
            batch_stats=new_bs,
        ), loss

    compiled = jax.jit(step, donate_argnums=0).lower(state, x, y).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))

    # Timing: chain iterations so in-order completion is provable (the
    # platform's block_until_ready can return early — utils/profiler.py).
    iters = 20
    for _ in range(3):
        state, loss = compiled(state, x, y)
    force(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = compiled(state, x, y)
    force(loss)
    dt = (time.perf_counter() - t0) / iters

    peak_flops, peak_bw, hbm_generation = chip_peaks()
    achieved_flops = flops / dt if flops else None
    achieved_bw = bytes_accessed / dt if bytes_accessed else None
    # Analytic cross-checks (telemetry/flops.py + memory.py): when the
    # measured XLA number and the formula disagree wildly, one of them
    # is lying about the workload — worth seeing in the artifact.  The
    # memory column puts the ledger's peak prediction beside the chip
    # allocator's real peak, per batch size.
    from ml_trainer_tpu.telemetry import memory as _memory
    from ml_trainer_tpu.telemetry.flops import train_step_flops

    analytic = train_step_flops(model, (batch, 224, 224, 3))
    mem_live = _memory.live_memory_snapshot()
    mem_ledger = _memory.bench_step_ledger(state, model, (x, y))
    row = {
        "model": model_name,
        "batch": batch,
        "hbm_peak_generation": hbm_generation,
        "step_ms": round(dt * 1e3, 3),
        "samples_per_sec": round(batch / dt, 1),
        "flops_per_step": flops,
        "flops_per_step_analytic": analytic,
        "bytes_per_step": bytes_accessed,
        "arith_intensity_flops_per_byte": (
            round(flops / bytes_accessed, 1) if bytes_accessed else None
        ),
        "peak_hbm_bytes": int(mem_live["max_peak_bytes_in_use"]),
        "analytic_hbm_bytes": int(mem_ledger.peak_bytes()),
        "analytic_hbm_resident_bytes": int(mem_ledger.resident_bytes()),
        "mfu": round(achieved_flops / peak_flops, 4) if achieved_flops else None,
        "hbm_utilization": (
            round(achieved_bw / peak_bw, 4) if achieved_bw else None
        ),
        # The machine balance of the chip: programs below this intensity
        # cannot reach peak FLOP/s no matter how well they schedule.
        "machine_balance_flops_per_byte": round(peak_flops / peak_bw, 1),
        "backend": jax.default_backend(),
    }
    # The verdict: which wall is closer.
    if row["mfu"] is not None and row["hbm_utilization"] is not None:
        row["bound"] = (
            "hbm" if row["hbm_utilization"] > row["mfu"] else "mxu"
        )
    return row


def kernel_columns(path=None):
    """Per-kernel before/after columns from the ``ops/kernels/``
    microbench artifact (``bench.py --kernels``): one row per kernel —
    reference (pre-kernel program) vs fused dispatch microseconds, the
    speedup, and the bit-parity pin — plus the engine-level decode
    step-time pair.  Returns None when the artifact is absent."""
    path = path or os.path.join(ROOT, "docs", "kernels_cpu.json")
    try:
        data = json.load(open(path))
    except (OSError, ValueError):
        return None
    rows = {}
    for name, row in (data.get("kernels") or {}).items():
        rows[name] = {
            "before_us": row.get("reference_us"),
            "after_us": row.get("kernel_us"),
            "speedup": row.get("speedup"),
            "parity": bool(
                row.get("interpret_parity") or row.get("trajectory_parity")
            ),
        }
    decode = data.get("decode") or {}
    return {
        "artifact": os.path.basename(path),
        "measured_backend": data.get("backend"),
        "rows": rows,
        "decode_step": {
            "before_us": decode.get("gather_step_us"),
            "after_us": decode.get("kernel_step_us"),
            "speedup": decode.get("kernel_vs_gather"),
        },
        "note": data.get("note"),
    }


def print_kernel_columns(cols) -> None:
    if not cols:
        print("# kernels: no docs/kernels_cpu.json — run "
              "`python bench.py --kernels` first", flush=True)
        return
    for name, row in cols["rows"].items():
        print(
            f"# kernel {name:>16} before {row['before_us']:>9,.1f} us  "
            f"after {row['after_us']:>9,.1f} us  x{row['speedup']:.2f}  "
            f"parity={'ok' if row['parity'] else 'BROKEN'}  "
            f"({cols['measured_backend']})", flush=True,
        )
    d = cols["decode_step"]
    if d.get("before_us"):
        print(
            f"# kernel {'decode_step':>16} before {d['before_us']:>9,.1f}"
            f" us  after {d['after_us']:>9,.1f} us  x{d['speedup']:.2f}  "
            f"(real engine)", flush=True,
        )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batches", default="32,128,256")
    ap.add_argument("--kernels-artifact", default=None, metavar="PATH",
                    help="kernel microbench artifact to read (default "
                    "docs/kernels_cpu.json)")
    ap.add_argument("--kernels-only", action="store_true",
                    help="print only the per-kernel before/after columns "
                    "from the kernels artifact and exit (no chip needed)")
    args = ap.parse_args()
    kernels = kernel_columns(args.kernels_artifact)
    if args.kernels_only:
        print_kernel_columns(kernels)
        sys.exit(0 if kernels else 1)
    from ml_trainer_tpu.utils.tunnel import acquire_tunnel_lock

    if not acquire_tunnel_lock(time.time() + 300.0, [],
                               label="mfu_ledger.py"):
        sys.exit("tunnel lock held by another client; try again later")
    assert jax.default_backend() == "tpu", (
        f"ledger needs the chip, got {jax.default_backend()}"
    )
    rows = []
    for b in (int(s) for s in args.batches.split(",")):
        row = measure(args.model, b)
        rows.append(row)
        print(json.dumps(row), flush=True)
    print_kernel_columns(kernels)
    out = os.path.join(ROOT, "docs", f"{args.model}_mfu_ledger.json")
    with open(out, "w") as fp:
        json.dump(
            {"device": str(jax.devices()[0]), "rows": rows,
             "kernels": kernels},
            fp, indent=1,
        )
    print(f"-> {out}")


if __name__ == "__main__":
    main()
