#!/bin/bash
# Tunnel-recovery watcher: wait for any in-flight runbook/recovery to
# finish (the tunnel serializes — concurrent clients wedge it), then
# probe the TPU every few minutes and run a resume-aware recovery pass
# (tpu_recover.sh) each time the probe answers.  Exits when
# `tpu_recover.sh --check` reports every artifact present, or after
# MAX_HOURS.
set -u
cd "$(dirname "$0")/.."
MAX_HOURS=${MAX_HOURS:-10}
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
LOG=/tmp/tpu_watch.log

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  # Never dial while another client owns the tunnel.
  # (the watcher's own --check / recovery calls run sequentially after
  # this pgrep, never concurrently with it, so matching tpu_recover.sh
  # here only catches a manually launched recovery — which is the point.
  # Patterns are anchored to interpreter invocations so an editor or grep
  # with one of these filenames in its argv does not park the watcher.)
  if pgrep -f "python[0-9.]* ([^ ]*/)?(bench\.py|bench_decode\.py|validate_flash_tpu\.py|mfu_ledger\.py|flash_tune\.py|make_notebooks\.py|01_local_training\.py)|bash ([^ ]*/)?(tpu_runbook\.sh|tpu_recover\.sh)$" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) busy: another TPU client running" >> "$LOG"
    sleep 300
    continue
  fi
  if bash scripts/tpu_recover.sh --check; then
    echo "$(date -u +%H:%M:%S) all artifacts present — watcher done" >> "$LOG"
    exit 0
  fi
  # -n (not -w): if another client holds the tunnel lock, skip this
  # cycle entirely — the watcher is the lowest-priority client and must
  # never make a driver-launched bench.py wait on ITS probe.  rc 75
  # (EX_TEMPFAIL) = lost the lock race, NOT a dead tunnel — logged
  # distinctly so the log reads correctly.  The probe doubles as the
  # keep-alive: a successful dial every cycle keeps the tunnel session
  # warm for whichever client (e.g. the driver's bench) comes next.
  flock -n -E 75 /tmp/tpu_tunnel.lock bash -c '
    echo "pid=$$ tpu_watch:probe $(date -u +%H:%M:%SZ)" \
      > /tmp/tpu_tunnel.holder
    exec timeout 180 python -u -c "import jax; jax.devices()"' \
    >/dev/null 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) probe OK — running recovery pass" >> "$LOG"
    bash scripts/tpu_recover.sh >> "$LOG" 2>&1
  elif [ "$rc" -eq 75 ]; then
    echo "$(date -u +%H:%M:%S) lock busy:" \
      "$(cat /tmp/tpu_tunnel.holder 2>/dev/null)" >> "$LOG"
  else
    echo "$(date -u +%H:%M:%S) probe failed" >> "$LOG"
  fi
  sleep 300
done
echo "$(date -u +%H:%M:%S) deadline reached" >> "$LOG"
