#!/usr/bin/env python
"""graft-lint CLI — jaxpr contract checks + AST lint over the real tree.

Runs both front ends of ``ml_trainer_tpu/analysis/``:

1. traces the ACTUAL train/eval/decode/prefill/verify programs (the
   same closures Trainer and SlotDecodeEngine build — tracing only,
   nothing compiles or executes on a device) and checks collective
   uniformity, bf16 dtype policy, donation/aliasing, host syncs;
2. parses ``ml_trainer_tpu/`` + ``scripts/`` and runs the concurrency
   and hygiene lints (lock-order cycles, unguarded shared state, device
   ops in host modules, hot-loop host syncs, unused imports).

Exit status: 0 when the findings match the committed baseline
(``docs/graft_lint_baseline.json`` — zero findings on a clean tree),
1 when NEW findings appeared.  ``--update-baseline`` rewrites the
artifact (a deliberate act, reviewed like any other diff).

    python scripts/graft_lint.py              # human report + gate
    python scripts/graft_lint.py --json out.json
    python scripts/graft_lint.py --ast-only   # skip program tracing
    python scripts/graft_lint.py --update-baseline
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Two virtual devices so the pipeline program (the lax.switch + ppermute
# composition the collective checker targets) is traceable on any host.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def gather_findings(ast_only: bool = False, jaxpr_only: bool = False,
                    with_lowered: bool = True):
    from ml_trainer_tpu import analysis

    report = analysis.Report()
    programs = []
    if not ast_only:
        from ml_trainer_tpu.analysis import jaxpr_checks, programs as P

        # Tracing each group IS the host-sync check for device code: a
        # .item()/float() inside a step closure raises at trace time and
        # lands as a host-sync-in-program finding, not a stack trace.
        groups = (
            ("train", lambda: P.build_train_specs(
                with_lowered=with_lowered)),
            ("decode", lambda: P.build_decode_specs(
                with_lowered=with_lowered)),
            ("pipeline", P.build_pipeline_specs),
        )
        for group_name, build in groups:
            specs: list = []
            report.extend(jaxpr_checks.check_traceable(
                lambda b=build, s=specs: s.extend(b()), group_name,
            ))
            for spec in specs:
                programs.append(spec.name)
                lowered = spec.lower_text() if spec.lower_text else None
                report.extend(jaxpr_checks.check_program(
                    spec.traced, spec.name, policy=spec.policy,
                    min_donation_bytes=spec.min_donation_bytes,
                    lowered_text=lowered,
                ))
    if not jaxpr_only:
        modules = analysis.scan_tree(REPO)
        report.extend(analysis.run_ast_checks(modules))
    return report, programs


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--json", metavar="PATH",
                        help="write the machine-readable report here "
                        "('-' for stdout)")
    parser.add_argument("--baseline", default=None,
                        help="baseline artifact (default: "
                        "docs/graft_lint_baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from this run's "
                        "findings")
    parser.add_argument("--ast-only", action="store_true",
                        help="skip program tracing (fast host-code lint)")
    parser.add_argument("--jaxpr-only", action="store_true",
                        help="skip the AST pack (program contracts only)")
    parser.add_argument("--no-lower", action="store_true",
                        help="skip the lowered-module aliasing "
                        "verification (faster)")
    args = parser.parse_args()

    from ml_trainer_tpu import analysis

    report, programs = gather_findings(
        ast_only=args.ast_only, jaxpr_only=args.jaxpr_only,
        with_lowered=not args.no_lower,
    )
    baseline_path = args.baseline or analysis.default_baseline_path()

    if args.update_baseline:
        payload = analysis.baseline_payload(report)
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w", encoding="utf-8") as fp:
            json.dump(payload, fp, indent=1, sort_keys=True)
            fp.write("\n")
        print(f"baseline updated: {baseline_path} "
              f"({len(report)} finding(s), "
              f"fingerprint {payload['fingerprint']})")

    baseline = analysis.load_baseline(baseline_path)
    diff = analysis.diff_against_baseline(report, baseline)

    machine = {
        "programs_traced": programs,
        **report.as_dict(),
        "baseline": diff,
    }
    if args.json == "-":
        print(json.dumps(machine, indent=1))
    elif args.json:
        with open(args.json, "w", encoding="utf-8") as fp:
            json.dump(machine, fp, indent=1)
        print(f"# report written: {args.json}")

    print(report.render())
    if programs:
        print(f"# traced {len(programs)} program(s): "
              + ", ".join(programs))
    if baseline is None:
        print("# no baseline artifact — every finding counts as new "
              "(run --update-baseline on a clean tree)")
    if diff["fixed"]:
        print(f"# {len(diff['fixed'])} baseline finding(s) no longer "
              "present — refresh the baseline when intentional:")
        for key in diff["fixed"]:
            print(f"#   fixed: {key}")
    if not diff["ok"]:
        print(f"GRAFT_LINT FAIL: {len(diff['new'])} new finding(s) vs "
              f"baseline {diff['baseline_fingerprint']}")
        return 1
    print(f"GRAFT_LINT OK: {len(report)} finding(s), all in baseline "
          f"(fingerprint {diff['fresh_fingerprint']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
