#!/usr/bin/env python
"""Fastlane smoke: memory ledger + goodput + recompile forensics.

A 2-virtual-device dryrun over the third observability pillar
(telemetry/memory.py, goodput.py, compile_watch.py), asserting the
acceptance invariants end to end through the REAL Trainer:

1. **Analytic-vs-measured agreement** (hard, 10%): the formula-driven
   ledger (``plan_train_memory`` — ``jax.eval_shape`` only, no state
   read) prices the state of {pure-DP, ZeRO-1 sharded-dp, 2-stage
   1F1B pipeline} configs within 10% of the MEASURED per-device buffer
   bytes of the live state (``measured_tree_bytes`` — real
   ``addressable_shards``).  ZeRO-1 must show the ÷2 moment shard,
   the pipeline must show the ÷2 stage shard.
2. **Goodput decomposition**: every run publishes a
   ``train_goodput_fraction`` in (0, 1] whose buckets + compute
   remainder reconstruct the wall-clock, with the compile bucket
   non-zero on a fresh process.
3. **Zero post-warmup compiles**: after each trainer's first epoch
   (train + eval programs built) the second epoch compiles NOTHING —
   ``compile_watch.post_warmup_count()`` stays 0 — and the compile
   counter named every program (``compile_events_total{fn=...}``).

Prints one ``MEMORY_SMOKE_RESULT {json}`` line (consumed by
``scripts/bench_gate.py gate_goodput`` and committed as
``docs/memory_goodput_cpu.json``), then ``MEMORY_SMOKE_OK``.  Exits
non-zero with a reason on any violation.  Runs on CPU in ~1 min.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

TOLERANCE = 0.10
GOODPUT_FLOOR = 0.02  # CPU floor: compiles dominate a tiny dryrun


def main() -> int:
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10, SyntheticTokens
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel import rules_for
    from ml_trainer_tpu.telemetry import compile_watch
    from ml_trainer_tpu.telemetry import memory as M
    from ml_trainer_tpu.telemetry.registry import default_registry
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    def fail(msg):
        print(f"MEMORY_SMOKE FAIL: {msg}")
        return 1

    assert jax.device_count() >= 2, "2-virtual-device mesh not active"
    workdir = tempfile.mkdtemp(prefix="memory_smoke_")
    t0 = custom_pre_process_function()

    def image_sets():
        return (SyntheticCIFAR10(size=64, seed=0, transform=t0),
                SyntheticCIFAR10(size=32, seed=1, transform=t0))

    result = {"configs": [], "backend": jax.default_backend()}

    def state_bytes_measured(trainer):
        measured, _ = M.measured_tree_bytes({
            "params": trainer.state.params,
            "opt_state": trainer.state.opt_state,
            "batch_stats": trainer.state.batch_stats,
        })
        return measured

    def analytic_state_bytes(ledger):
        return sum(
            c.bytes for c in ledger.components
            if c.name in ("params", "opt_state", "batch_stats")
        )

    # ---- leg 1/2: pure-DP and ZeRO-1 sharded-dp over data=2 ------------
    for label, extra in (
        ("pure_dp", {}),
        ("zero1_sharded_dp", {"dp_update": "sharded"}),
    ):
        before = compile_watch.post_warmup_count()
        t = Trainer(
            MLModel(), datasets=image_sets(), epochs=2, batch_size=16,
            model_dir=os.path.join(workdir, label), metric=None, lr=0.01,
            optimizer="adamw", mesh_shape={"data": 2}, telemetry=True,
            log_every_steps=1, **extra,
        )
        t.fit()
        if compile_watch.post_warmup_count() != before:
            return fail(
                f"{label}: {compile_watch.post_warmup_count() - before} "
                f"post-warmup recompile(s): "
                f"{[e.as_dict() for e in compile_watch.events(last=4)]}"
            )
        # Formula planner (no state read) vs the measured live buffers.
        plan = M.plan_train_memory(
            MLModel(), t._batch_geometry, optimizer="adamw",
            mesh_shape={"data": 2},
            dp_update=extra.get("dp_update", "fused"),
        )
        measured = state_bytes_measured(t)
        check = M.cross_check(
            analytic_state_bytes(plan), measured, TOLERANCE
        )
        row = {"config": label, **check}
        result["configs"].append(row)
        if not check["ok"]:
            return fail(f"{label}: analytic vs measured disagree: {check}")
        if label == "zero1_sharded_dp":
            # The ÷2 must be visible: sharded moments cost LESS than the
            # pure-DP replicated ones did.
            rep = next(
                r for r in result["configs"] if r["config"] == "pure_dp"
            )
            if check["measured_bytes"] >= rep["measured_bytes"]:
                return fail(
                    "ZeRO-1 state not smaller than replicated: "
                    f"{check['measured_bytes']} >= {rep['measured_bytes']}"
                )
        print(f"# memory smoke: {label} analytic/measured "
              f"{check['ratio']:.3f} OK")

    # ---- leg 3: 2-stage 1F1B pipeline over a stage mesh ----------------
    before = compile_watch.post_warmup_count()
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    from ml_trainer_tpu.parallel import create_mesh

    mesh = create_mesh({"stage": 2}, devices=jax.devices()[:2])
    pipe_model = get_model(
        "gpt2_pipe_tiny", n_stages=2, num_heads=2, mesh=mesh,
        n_microbatches=4,
    )
    t_pp = Trainer(
        pipe_model, datasets=(ds, ds),
        model_dir=os.path.join(workdir, "pipeline"),
        epochs=2, batch_size=8, seed=3, lr=0.01, optimizer="adamw",
        metric=None, mesh_shape={"stage": 2},
        sharding_rules=rules_for("gpt2", "pp"),
        pipeline_schedule="1f1b", telemetry=True, log_every_steps=2,
    )
    t_pp.fit()
    if compile_watch.post_warmup_count() != before:
        return fail(
            f"pipeline: {compile_watch.post_warmup_count() - before} "
            "post-warmup recompile(s)"
        )
    plan = M.plan_train_memory(
        get_model("gpt2_pipe_tiny", n_stages=2, num_heads=2,
                  n_microbatches=4),
        t_pp._batch_geometry, optimizer="adamw",
        mesh_shape={"stage": 2}, sharding_rules=rules_for("gpt2", "pp"),
    )
    measured = state_bytes_measured(t_pp)
    check = M.cross_check(analytic_state_bytes(plan), measured, TOLERANCE)
    result["configs"].append({"config": "pipeline_1f1b_s2", **check})
    if not check["ok"]:
        return fail(f"pipeline: analytic vs measured disagree: {check}")
    # The trainer's own ledger priced the pipeline stash.
    stash = t_pp._memory_ledger.component("pipeline_stash")
    if stash is None or stash.bytes <= 0:
        return fail("trainer ledger missing the pipeline_stash component")
    result["pipeline_stash_bytes"] = int(stash.bytes)
    print(f"# memory smoke: pipeline_1f1b_s2 analytic/measured "
          f"{check['ratio']:.3f}, stash {int(stash.bytes)} bytes OK")

    # ---- goodput decomposition ----------------------------------------
    gp = t_pp._telemetry.goodput.last
    if gp is None:
        return fail("goodput meter never reported")
    recon = gp["compute_secs"] + sum(gp["buckets_secs"].values())
    if abs(recon - gp["wall_secs"]) > max(
        gp["overshoot_secs"] + 1e-6, 0.01 * gp["wall_secs"]
    ):
        return fail(
            f"goodput buckets do not reconstruct the wall clock: "
            f"{recon} vs {gp['wall_secs']}"
        )
    snap = default_registry().snapshot()
    frac = snap.get("train_goodput_fraction", 0.0)
    if not (GOODPUT_FLOOR <= frac <= 1.0):
        return fail(f"goodput fraction {frac} outside "
                    f"[{GOODPUT_FLOOR}, 1.0]")
    if snap.get(
        "train_goodput_seconds_total{bucket=compile}", 0.0
    ) <= 0.0:
        return fail("compile bucket empty on a fresh process")
    result["goodput"] = {
        "fraction": round(frac, 4),
        "buckets_secs": {
            b: round(v, 3) for b, v in gp["buckets_secs"].items()
        },
        "compute_secs": round(gp["compute_secs"], 3),
        "wall_secs": round(gp["wall_secs"], 3),
    }

    # ---- compile forensics --------------------------------------------
    by_fn = compile_watch.counts_by_fn()
    train_compiles = sum(
        v for k, v in by_fn.items() if "train_step" in k
    )
    if train_compiles < 2:  # the per-batch step of >= 2 of the trainers
        return fail(f"compile counter missed the train steps: {by_fn}")
    result["compiles"] = {
        "total": compile_watch.compile_count(),
        "post_warmup": compile_watch.post_warmup_count(),
        "train_step": train_compiles,
        "mode": compile_watch.install(),
    }
    # Live-vs-analytic exposition both landed in the registry.
    for key in ("mem_analytic_resident_bytes", "mem_live_bytes{device=0}"):
        if key not in snap:
            return fail(f"registry missing {key!r}")

    print("MEMORY_SMOKE_RESULT " + json.dumps(result))
    print(
        "MEMORY_SMOKE_OK: "
        f"{len(result['configs'])} configs within {TOLERANCE:.0%}, "
        f"goodput {result['goodput']['fraction']}, "
        f"{result['compiles']['total']} compiles "
        f"({result['compiles']['post_warmup']} post-warmup)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
