"""Decode-path performance: prefill tokens/s, steady-state per-token
latency, greedy vs beam, and the GQA cache-size win — measured, not
claimed (VERDICT r4 weak #7: the generation stack had zero performance
evidence).

The reference has no serving path at all (SURVEY.md §1), so there is no
reference row to beat; these numbers exist so "fast decode" is a
measurement.  Method:

- One compiled program per (model, shape, horizon) — the ``generate``
  program cache.  First call compiles (excluded); timed calls are the
  median of ``--reps`` fenced repeats (``profiler.force`` documents this
  platform returning from ``block_until_ready`` early).
- Steady-state per-token latency is a two-horizon difference:
  ``(t(H_long) - t(H_short)) / (H_long - H_short)`` — subtracting the
  shared prefill + dispatch cost instead of guessing it.
- Prefill tokens/s backs the one-step horizon out of ``t(H_short)``:
  ``B*P / (t_short - H_short*per_token)``.
- The GQA win is the measured byte size of the llama decode cache vs the
  same model built with ``num_kv_heads == num_heads`` (MHA): K/V leaves
  shrink by exactly H/Hkv; the measured ratio is computed from real
  cache pytrees, not the formula.

Writes one JSON document (``--out``, default docs/decode_bench.json on
TPU, stdout always).  CPU smoke: ``--models gpt2_tiny,llama_tiny --cpu``.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.generate import _cache_shapes, beam_search, generate
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.utils.profiler import force
from ml_trainer_tpu.utils.tunnel import acquire_tunnel_lock

# (batch, prompt_len, short horizon, long horizon) per benched model.
# Prompt fills half the context; horizons stay inside max_len.
SHAPES = {
    "gpt2": (8, 512, 16, 144),
    "llama": (8, 512, 16, 144),
    "gpt2_tiny": (4, 32, 4, 20),
    "llama_tiny": (4, 32, 4, 20),
}
BEAMS = 4


def _timed(fn, reps):
    """Median wall seconds of ``reps`` fenced calls (post-compile)."""
    out = fn()  # compile + warm
    force(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        force(out)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _cache_bytes(model, b):
    dm = model.clone(decode=True)
    shapes = _cache_shapes(dm, b, jnp.int32)
    return sum(
        int(np.prod(s.shape)) * s.dtype.itemsize
        for s in jax.tree.leaves(shapes)
    )


def bench_model(name, reps):
    b, p, h_short, h_long = SHAPES[name]
    model = get_model(name, dtype=jnp.bfloat16)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, model.vocab_size, (b, p)),
        jnp.int32,
    )
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, prompt[:, :1], train=False
    )

    t_short = _timed(
        lambda: generate(model, variables, prompt, h_short), reps
    )
    t_long = _timed(
        lambda: generate(model, variables, prompt, h_long), reps
    )
    per_tok = (t_long - t_short) / (h_long - h_short)
    prefill_s = max(t_short - h_short * per_tok, 1e-9)
    row = {
        "model": name,
        "batch": b,
        "prompt_len": p,
        "greedy": {
            "per_token_ms": round(per_tok * 1e3, 3),
            "decode_tokens_per_sec": round(b / per_tok, 1),
            "prefill_tokens_per_sec": round(b * p / prefill_s, 1),
            "horizons": [h_short, h_long],
        },
    }

    tb_short = _timed(
        lambda: beam_search(model, variables, prompt, h_short,
                            num_beams=BEAMS), reps
    )
    tb_long = _timed(
        lambda: beam_search(model, variables, prompt, h_long,
                            num_beams=BEAMS), reps
    )
    beam_tok = (tb_long - tb_short) / (h_long - h_short)
    row["beam"] = {
        "num_beams": BEAMS,
        "per_token_ms": round(beam_tok * 1e3, 3),
        # B*K candidate sequences advance per step.
        "decode_tokens_per_sec": round(b * BEAMS / beam_tok, 1),
        "vs_greedy_per_token": round(beam_tok / per_tok, 2),
    }

    if "llama" in name:
        gqa = _cache_bytes(model, b)
        mha = _cache_bytes(
            get_model(name, dtype=jnp.bfloat16,
                      num_kv_heads=model.num_heads), b
        )
        row["gqa_cache"] = {
            "bytes": gqa,
            "mha_equivalent_bytes": mha,
            "ratio": round(mha / gqa, 2),
            "num_heads": model.num_heads,
            "num_kv_heads": model.num_kv_heads,
        }
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="gpt2,llama",
                    help="comma list from %s" % sorted(SHAPES))
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (smoke run; no file written "
                    "unless --out is given)")
    ap.add_argument("--out", default=None,
                    help="output path (default docs/decode_bench.json "
                    "when the backend is TPU)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        # Standalone runs dial the tunnel: serialize against every other
        # client (no-op when a parent recovery stage already holds the
        # lock and exported TPU_TUNNEL_LOCK_HELD=1).
        lock_log: list = []
        if not acquire_tunnel_lock(time.time() + 300.0, lock_log,
                                   label="bench_decode.py"):
            print(json.dumps(
                {"error": "tunnel lock held by another client",
                 "probe": lock_log}
            ))
            sys.exit(1)

    dev = jax.devices()[0]
    doc = {
        "device": str(dev.device_kind),
        "backend": "cpu" if args.cpu or dev.platform == "cpu" else "tpu",
        "measured": time.strftime("%Y-%m-%d %H:%MZ", time.gmtime()),
        "reps": args.reps,
        "rows": [],
    }
    for name in args.models.split(","):
        name = name.strip()
        print(f"# decode bench: {name}", file=sys.stderr, flush=True)
        doc["rows"].append(bench_model(name, args.reps))

    out = args.out
    if out is None and doc["backend"] == "tpu":
        out = "docs/decode_bench.json"
    if out:
        Path(out).write_text(json.dumps(doc, indent=1) + "\n")
        print(f"# wrote {out}", file=sys.stderr)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
