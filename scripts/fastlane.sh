#!/usr/bin/env bash
# The tier-1 verify gate, EXACTLY as ROADMAP.md specifies it — one
# committed wrapper so the builder and the reviewer run the identical
# command (pipefail, CPU pinned, fast lane only, DOTS_PASSED count) —
# plus a fault-injection smoke leg (scripts/chaos_smoke.py) covering the
# resilience layer's env-var plumbing end to end, a telemetry smoke
# leg (scripts/telemetry_smoke.py) covering the observability spine
# (registry gauges, Prometheus exposition, spans, flight dumps, cluster
# aggregation, run report, comm-bytes accounting), a paged-serving
# smoke leg (scripts/paged_serving_smoke.py) covering the PR6 paged KV
# + prefix cache + preempt-requeue stack end to end, a mixed-precision /
# sharded-update smoke leg (scripts/mixed_smoke.py: 2-virtual-device
# bucketed-overlap + bf16 dryrun, zero recompiles, finite loss,
# overflow-backoff semantics), a pipeline-schedule smoke leg
# (scripts/pipeline_smoke.py: 1F1B + interleaved through the real
# Trainer on a 2-virtual-device stage mesh, serial-fold trajectory
# equality, zero recompiles, per-hop comm + bubble gauges), a memory /
# goodput / recompile smoke leg (scripts/memory_smoke.py: analytic HBM
# ledger within 10% of measured state bytes on pure-DP / ZeRO-1 /
# pipeline configs, goodput bucket arithmetic, zero post-warmup
# compiles), a serving-SLO smoke leg (scripts/slo_smoke.py: open-loop
# Poisson schedule through the real HTTP server — lifecycle latency
# histograms + attainment/burn-rate exposition, nested request trace
# spans, forced-preemption flight dump naming request ids with
# timelines), a batched-LoRA serving smoke leg (scripts/lora_smoke.py:
# 8 adapters + base traffic interleaved over the real HTTP server —
# adapter=None byte identity, per-adapter prefix-cache isolation,
# hot-load under live load with zero recompiles, adapter pool gauges on
# /metrics and adapters_resident on /healthz), a disaggregated-router
# smoke leg
# (scripts/router_smoke.py: 2-replica in-process router — byte
# identity through page-granular KV migration, router_* metrics on the
# /metrics scrape, session stickiness, replica-kill
# drain-and-redistribute with structured errors past the budget), an
# overload/failure-survival smoke leg (scripts/overload_smoke.py:
# real HTTP fleet — circuit breaker opens on an injected wedge with
# byte-identical redistribution, degradation ladder engages/exits with
# structured 503 + Retry-After sheds, SLO-burn autoscaler replaces a
# killed replica, and serving_degradation_level / router_hedges_total /
# router_breaker_state / autoscaler_actions_total land on /metrics), an
# elastic-training smoke leg (scripts/elastic_smoke.py
# --quick: kill 1 of 2 simulated hosts mid-run; the same fit() drains,
# reshapes 8 -> 4 devices and finishes with the uninterrupted
# trajectory and a bit-exact-resumable history; the bench gate's
# gate_elastic adds the cross-process hard-kill restart +
# time-to-recover ratchet vs docs/elastic_chaos_cpu.json), and a bench
# graft-lint static-analysis leg (scripts/graft_lint.py: jaxpr
# contract checks over the traced train/decode/pipeline programs +
# the AST concurrency/hygiene pack, hard-failed against the committed
# docs/graft_lint_baseline.json), a ruff import-hygiene leg (pyproject
# [tool.ruff]; skipped when ruff is not installed — graft-lint's
# unused-import rule enforces the F401 subset either way), and a bench
# regression gate (scripts/bench_gate.py) that fails on >10% samples/s
# regression vs the committed BENCH trajectory / this machine's
# calibrated baseline — plus the paged-serving replay gate (byte
# identity, zero-recompile, paged-vs-contiguous ratio, tokens/s ratchet
# vs docs/serving_replay_cpu.json), the mixed gate (finite/zero-recompile
# invariants, sharded>=fused floor, ratchet vs
# docs/mixed_precision_cpu.json), the pipeline gate (trajectory
# equality + zero-recompile invariants, 1f1b>=gpipe floor at S=4/M=8,
# ratchet vs docs/pipeline_schedules_cpu.json), and the serving-SLO
# gate (zero-recompile + zero-error invariants at the committed
# artifact's highest offered rate, tokens/s ratchet vs
# docs/serving_slo_cpu.json), and the disaggregated-router gate
# (byte identity between topologies, zero recompiles, migration
# coverage, disaggregated tokens/s ratchet vs
# docs/serving_disagg_cpu.json; --skip-disagg to skip), and the
# overload gate (serving chaos: kill + slow with vs without the
# mitigation stack — identity/recompile/structured-error invariants
# hard, mitigated-vs-baseline attainment floor, chaos-attainment
# ratchet vs docs/serving_chaos_cpu.json; --skip-overload to skip),
# and a multi-process serving-fleet smoke leg (scripts/fleet_smoke.py:
# 4 REAL worker processes driven only over HTTP sockets — byte
# identity through socket KV migration + chunked prefill, a real
# SIGKILL mid-stream redistributed byte-identical, the autoscaler
# respawning a real replacement process) backed by the fleet gate
# (bench_gate.py gate_fleet: identity/zero-recompile/chunk-coverage/
# chaos-recovery invariants hard, fleet tokens/s ratchet vs
# docs/serving_fleet_cpu.json; --skip-fleet to skip), a fleet
# observability-plane smoke leg (scripts/fleet_obs_smoke.py: a real
# 3-process fleet under the router's metrics federation — every worker
# series re-exported on the router /metrics with replica/role/
# generation labels and idempotent re-scrape — plus one clock-aligned
# merged Perfetto trace with a migrated request crossing process lanes
# in causal order, and a SIGKILL-triggered incident bundle holding the
# surviving replicas' flight dumps and the dead worker's stderr tail;
# the fleet gate hard-pins the same invariants live via
# bench.bench_fleet_obs and ratchets vs docs/fleet_obs_cpu.json), and
# a Pallas
# kernel-layer smoke leg (scripts/kernels_smoke.py: interpret-mode
# bit parity for the paged-attention / fused-Adam / int8-matmul
# kernels vs their lax references, real-Server byte identity gather
# vs paged_kernel with zero post-warmup recompiles, fused-vs-optax
# sharded-Adam bit-identical trainer golden, structured refusals, and
# the int8 argmax-agreement quality gate) backed by the kernels gate
# (bench_gate.py gate_kernels: parity/identity/zero-recompile
# invariants hard, kernel-vs-gather ratio floor, decode steps/s
# ratchet vs docs/kernels_cpu.json; --skip-kernels to skip), and a
# live-rollout smoke leg (scripts/deploy_smoke.py: Trainer.fit a tiny
# gpt2, export it manifest + weights fingerprint, and Router.deploy
# the export onto a live 2-process fleet MID-LOAD — canary -> ramp ->
# promote with zero dropped streams, zero steady-fleet recompiles and
# byte-identical outputs, then a wedged-factory canary regression
# auto-rolled-back within one burn window) backed by the deploy gate
# (bench_gate.py gate_deploy: deploy/rollback-verdict, rollback-
# latency, identity, zero-recompile and fingerprint invariants hard,
# post-rollback tokens/s ratchet vs docs/serving_deploy_cpu.json;
# --skip-deploy to skip), and a watchtower smoke leg
# (scripts/watchtower_smoke.py: the in-process TSDB + declarative
# alert engine + live /dash dashboard against a real 3-process fleet —
# byte identity and zero post-warmup compiles with the plane on, a
# replica_slow chaos fault detected by a runtime-installed
# severity-page AlertRule within one evaluation window, firing the
# flight alert record and an incident bundle holding dashboard.html +
# alerts.json) backed by the watchtower gate (bench_gate.py
# gate_watchtower: first-eval detection / ring-bound / dump-roundtrip
# invariants hard, registry-sweep ratchet vs docs/watchtower_cpu.json,
# perf_diff attribution printed under a failed ratchet;
# --skip-watchtower to skip).
#
# Every leg's wall-clock is upserted into docs/fastlane_timings.json
# (scripts/perf_diff.py record) — diff two of those files with
# scripts/perf_diff.py to attribute a fastlane slowdown to its leg.
#
# On a PR branch (HEAD != origin/main with origin/main resolvable) the
# bench gate runs in --changed-only mode: the diff's files map to gate
# legs (scripts/bench_gate.py legs_for_changes) so a docs-only PR
# skips the heavy legs entirely.  FULL_GATE=1 forces the full run.
#
#   ./scripts/fastlane.sh            # from the repo root
#   FULL_GATE=1 ./scripts/fastlane.sh  # full bench gate regardless of diff
#
# Exits non-zero if either leg fails; prints DOTS_PASSED=<n> as the
# last line (the tier-1 count, unchanged by the smoke leg).
set -o pipefail
cd "$(dirname "$0")/.." || exit 1

# Per-leg wall-clock ledger: every leg upserts its seconds (and rc)
# into docs/fastlane_timings.json — itself a perf_diff-able artifact,
# so "fastlane got slow" attributes to a leg, not a feeling.
TIMINGS=docs/fastlane_timings.json
record_leg() {  # record_leg <name> <seconds> <rc>
  python scripts/perf_diff.py record --file "$TIMINGS" \
    --leg "$1" --seconds "$2" --rc "$3" >/dev/null 2>&1 || true
}
run_leg() {  # run_leg <name> <timeout_s> <script...>; returns leg rc
  local name=$1 tmo=$2 t0 leg_rc
  shift 2
  t0=$SECONDS
  timeout -k 10 "$tmo" env JAX_PLATFORMS=cpu "$@"
  leg_rc=$?
  record_leg "$name" $((SECONDS - t0)) $leg_rc
  [ $leg_rc -ne 0 ] && echo "# $name leg FAILED (rc=$leg_rc)"
  return $leg_rc
}

rm -f /tmp/_t1.log
t0=$SECONDS
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
record_leg tier1 $((SECONDS - t0)) $rc
echo "# fault-injection smoke leg"
run_leg chaos 240 python scripts/chaos_smoke.py
smoke_rc=$?
echo "# telemetry smoke leg"
run_leg telemetry 240 python scripts/telemetry_smoke.py
telemetry_rc=$?
echo "# paged serving smoke leg"
run_leg paged_serving 300 python scripts/paged_serving_smoke.py
paged_rc=$?
echo "# mixed-precision / sharded-update smoke leg"
run_leg mixed 300 python scripts/mixed_smoke.py
mixed_rc=$?
echo "# pipeline-schedule smoke leg"
run_leg pipeline 300 python scripts/pipeline_smoke.py
pipeline_rc=$?
echo "# memory ledger / goodput / recompile smoke leg"
run_leg memory 300 python scripts/memory_smoke.py
memory_rc=$?
echo "# serving-SLO smoke leg"
run_leg slo 300 python scripts/slo_smoke.py
slo_rc=$?
echo "# batched-LoRA serving smoke leg"
run_leg lora 400 python scripts/lora_smoke.py
lora_rc=$?
echo "# disaggregated-router smoke leg"
run_leg router 400 python scripts/router_smoke.py
router_rc=$?
echo "# overload/failure-survival smoke leg"
run_leg overload 400 python scripts/overload_smoke.py
overload_rc=$?
echo "# elastic-training smoke leg (--quick: in-process reshape only;"
echo "# the bench gate's gate_elastic runs the full cross-process leg)"
run_leg elastic 300 python scripts/elastic_smoke.py --quick
elastic_rc=$?
echo "# multi-process serving-fleet smoke leg"
run_leg fleet 500 python scripts/fleet_smoke.py
fleet_rc=$?
echo "# fleet observability-plane smoke leg"
run_leg fleet_obs 500 python scripts/fleet_obs_smoke.py
fleet_obs_rc=$?
echo "# watchtower (TSDB + alert rules + dashboard) smoke leg"
run_leg watchtower 500 python scripts/watchtower_smoke.py
watchtower_rc=$?
echo "# live-rollout (canary deploy + auto-rollback) smoke leg"
run_leg deploy 500 python scripts/deploy_smoke.py
deploy_rc=$?
echo "# Pallas kernel-layer smoke leg"
run_leg kernels 300 python scripts/kernels_smoke.py
kernels_rc=$?
echo "# graft-lint static-analysis leg"
run_leg graft_lint 300 python scripts/graft_lint.py
lint_rc=$?
echo "# ruff import-hygiene leg (when installed; graft-lint's"
echo "# unused-import rule covers the F401 subset regardless)"
if command -v ruff >/dev/null 2>&1; then
  ruff check ml_trainer_tpu scripts
  ruff_rc=$?
  [ $ruff_rc -ne 0 ] && echo "# ruff FAILED (rc=$ruff_rc)"
else
  echo "# ruff not installed; skipped"
  ruff_rc=0
fi
echo "# bench regression gate"
# On a PR branch, map the diff vs origin/main to gate legs and run
# only those (--changed-only); FULL_GATE=1 or a missing/identical
# origin/main falls back to the full gate.
gate_args=""
if [ -z "$FULL_GATE" ] \
  && git rev-parse --verify -q origin/main >/dev/null 2>&1 \
  && [ "$(git rev-parse HEAD)" != "$(git rev-parse origin/main)" ]; then
  gate_args="--changed-only"
  echo "# (PR branch: bench gate in --changed-only mode; FULL_GATE=1 overrides)"
fi
t0=$SECONDS
timeout -k 10 3000 env JAX_PLATFORMS=cpu python scripts/bench_gate.py $gate_args
gate_rc=$?
record_leg bench_gate $((SECONDS - t0)) $gate_rc
[ $gate_rc -ne 0 ] && echo "# bench gate FAILED (rc=$gate_rc)"
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
[ $rc -eq 0 ] && rc=$smoke_rc
[ $rc -eq 0 ] && rc=$telemetry_rc
[ $rc -eq 0 ] && rc=$paged_rc
[ $rc -eq 0 ] && rc=$mixed_rc
[ $rc -eq 0 ] && rc=$pipeline_rc
[ $rc -eq 0 ] && rc=$memory_rc
[ $rc -eq 0 ] && rc=$slo_rc
[ $rc -eq 0 ] && rc=$lora_rc
[ $rc -eq 0 ] && rc=$router_rc
[ $rc -eq 0 ] && rc=$overload_rc
[ $rc -eq 0 ] && rc=$elastic_rc
[ $rc -eq 0 ] && rc=$fleet_rc
[ $rc -eq 0 ] && rc=$fleet_obs_rc
[ $rc -eq 0 ] && rc=$watchtower_rc
[ $rc -eq 0 ] && rc=$deploy_rc
[ $rc -eq 0 ] && rc=$kernels_rc
[ $rc -eq 0 ] && rc=$lint_rc
[ $rc -eq 0 ] && rc=$ruff_rc
[ $rc -eq 0 ] && rc=$gate_rc
exit $rc
