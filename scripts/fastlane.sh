#!/usr/bin/env bash
# The tier-1 verify gate, EXACTLY as ROADMAP.md specifies it — one
# committed wrapper so the builder and the reviewer run the identical
# command (pipefail, CPU pinned, fast lane only, DOTS_PASSED count).
#
#   ./scripts/fastlane.sh            # from the repo root
#
# Exits with pytest's status; prints DOTS_PASSED=<n> as the last line.
set -o pipefail
cd "$(dirname "$0")/.." || exit 1
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
