#!/usr/bin/env python
"""Telemetry smoke leg (scripts/fastlane.sh) — ~30s on CPU.

One tiny end-to-end pass over the telemetry spine's cheap guarantees,
as a standalone script so the fast lane exercises the REAL env-var
plumbing (flight-dir redirect, JSONL sink), not just the programmatic
test hooks:

1. A one-epoch ``Trainer(telemetry=True)`` run with an injected
   ``nan_grad`` + rollback emits train gauges into the default
   registry, writes a ``history.json`` mirror ``load_history`` prefers,
   and dumps a flight record naming the offending step.
2. The registry round-trips through Prometheus text exposition
   (headers + samples parse) and the JSONL sink appends parseable
   lines.
3. The span buffer holds the run's ``data_load`` / ``h2d`` /
   ``ckpt_write`` spans and saves a loadable Perfetto trace.
4. The distributed-observability leg, single-process degenerate case:
   the trainer's cluster aggregation published ``cluster_*{host=0}``
   series and a ``run_report.json``/``.md`` pair, and a sharded dryrun
   step (``shard_map`` + explicit collectives over a 2-virtual-device
   mesh) left ``comm_bytes_total{op=...}`` gauges behind.

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Two virtual CPU devices so the comm-bytes leg has a real axis to
# collect over (the trainer legs keep their single-device mesh).
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.environ["ML_TRAINER_TPU_FLIGHT_DIR"] = workdir
    os.environ["ML_TRAINER_TPU_METRICS_JSONL"] = os.path.join(
        workdir, "metrics.jsonl"
    )

    from ml_trainer_tpu import Trainer, MLModel, load_history
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.telemetry import (
        default_registry,
        prometheus_text,
        save_trace,
        trace_events,
    )
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    def fail(msg):
        print(f"TELEMETRY_SMOKE FAIL: {msg}")
        return 1

    t0 = custom_pre_process_function()
    with faults.injected("nan_grad@step=3"):
        t = Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0, transform=t0),
                      SyntheticCIFAR10(size=32, seed=1, transform=t0)),
            epochs=1, batch_size=16, model_dir=workdir, metric=None,
            lr=0.01, save_history=True, telemetry=True, log_every_steps=1,
            rollback_bad_steps=1,
        )
        t.fit()

    # 1. Registry gauges + flight dump + history.json.
    snap = default_registry().snapshot()
    if snap.get("train_skipped_steps_total", 0) < 1:
        return fail(f"skipped-step counter not published: {snap}")
    # The real recompile instrument (telemetry/compile_watch.py) replaces
    # the old per-function _cache_size() pin: the train step compiled
    # exactly once, and the labeled counter reached the registry.
    from ml_trainer_tpu.telemetry import compile_watch

    if compile_watch.compile_count("jit(train_step)") != 1:
        return fail(
            f"telemetry caused recompiles: {compile_watch.counts_by_fn()}"
        )
    if snap.get("compile_events_total{fn=jit(train_step)}") != 1:
        return fail("compile_events_total{fn=} counter not published")
    dumps = [f for f in os.listdir(workdir) if f.startswith("flight_")]
    if not dumps:
        return fail("no flight dump after nan_grad rollback")
    payload = json.load(open(os.path.join(workdir, dumps[0])))
    if payload.get("first_bad_step") != 3:
        return fail(f"flight dump does not name step 3: {payload.get('first_bad_step')}")
    # OOM/wedge forensics ride along: the dump attaches the device-memory
    # snapshot and the recent compile events (flight context providers).
    ctx = payload.get("context", {})
    if "live" not in ctx.get("memory", {}):
        return fail(f"flight dump missing memory snapshot: {list(ctx)}")
    if not isinstance(ctx.get("compile_events"), list):
        return fail(f"flight dump missing compile events: {list(ctx)}")
    hist = load_history(workdir)
    if hist.get("rollbacks") != 1 or sum(hist.get("skipped_steps", [])) != 1:
        return fail(f"history.json resilience ledger wrong: {hist}")

    # 2. Prometheus text + JSONL sink.
    text = prometheus_text(default_registry())
    if "# TYPE train_grad_norm gauge" not in text:
        return fail("prometheus exposition missing train gauges")
    for line in text.splitlines():
        if not (line.startswith("#") or " " in line):
            return fail(f"malformed exposition line: {line!r}")
    with open(os.environ["ML_TRAINER_TPU_METRICS_JSONL"]) as fp:
        lines = [json.loads(ln) for ln in fp if ln.strip()]
    if not any(ln.get("kind") == "train_step" for ln in lines):
        return fail("JSONL sink holds no train_step events")

    # 3. Spans: the run's host regions are on the trace.
    names = {e["name"] for e in trace_events()}
    for expected in ("data_load", "h2d", "ckpt_write"):
        if expected not in names:
            return fail(f"span {expected!r} missing from trace ({names})")
    trace_path = save_trace(os.path.join(workdir, "trace.json"))
    loaded = json.load(open(trace_path))
    if not loaded.get("traceEvents"):
        return fail("saved Perfetto trace is empty")

    # 4. Distributed observability, degenerate single-host case.
    for key in ("cluster_last_step{host=0}", "cluster_step_ms_p50{host=0}",
                "cluster_syncs_total"):
        if key not in default_registry().snapshot():
            return fail(f"cluster aggregation missing {key!r}")
    report_path = os.path.join(workdir, "run_report.json")
    if not os.path.exists(report_path):
        return fail("trainer did not write run_report.json")
    report = json.load(open(report_path))
    for section in ("throughput", "hosts", "comm_bytes_by_op", "resilience"):
        if section not in report:
            return fail(f"run report missing section {section!r}")
    if report["resilience"].get("rollbacks") != 1:
        return fail(f"run report missed the rollback: {report['resilience']}")
    if not os.path.exists(os.path.join(workdir, "run_report.md")):
        return fail("run_report.md missing")

    # Comm-bytes gauges after one sharded (shard_map + explicit
    # collective) step over the 2-virtual-device mesh.
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.collectives import psum
    from ml_trainer_tpu.parallel.compat import shard_map

    if jax.device_count() < 2:
        return fail(f"expected 2 virtual devices, got {jax.device_count()}")
    mesh = create_mesh({"data": 2}, devices=jax.devices()[:2])
    step = jax.jit(shard_map(
        lambda x: psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ))
    step(jnp.ones((4, 8), jnp.float32)).block_until_ready()
    snap = default_registry().snapshot()
    comm = snap.get("comm_bytes_total{op=psum}", 0)
    # per-shard (2, 8) f32 = 64 bytes; ring all-reduce over 2 devices
    # moves 2 * 64 * 1/2 = 64 bytes per participant.
    if comm < 64:
        return fail(f"comm_bytes_total{{op=psum}} not published: {comm}")

    print(
        "TELEMETRY_SMOKE OK: "
        f"{int(snap['train_steps_total'])} steps telemetered, "
        f"flight dump {dumps[0]} names step 3, "
        f"{len(loaded['traceEvents'])} trace events, "
        f"{len(lines)} JSONL records, "
        f"cluster series + run report present, "
        f"psum comm bytes {int(comm)}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
