#!/usr/bin/env python
"""Telemetry smoke leg (scripts/fastlane.sh) — ~30s on CPU.

One tiny end-to-end pass over the telemetry spine's cheap guarantees,
as a standalone script so the fast lane exercises the REAL env-var
plumbing (flight-dir redirect, JSONL sink), not just the programmatic
test hooks:

1. A one-epoch ``Trainer(telemetry=True)`` run with an injected
   ``nan_grad`` + rollback emits train gauges into the default
   registry, writes a ``history.json`` mirror ``load_history`` prefers,
   and dumps a flight record naming the offending step.
2. The registry round-trips through Prometheus text exposition
   (headers + samples parse) and the JSONL sink appends parseable
   lines.
3. The span buffer holds the run's ``data_load`` / ``h2d`` /
   ``ckpt_write`` spans and saves a loadable Perfetto trace.

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.environ["ML_TRAINER_TPU_FLIGHT_DIR"] = workdir
    os.environ["ML_TRAINER_TPU_METRICS_JSONL"] = os.path.join(
        workdir, "metrics.jsonl"
    )

    from ml_trainer_tpu import Trainer, MLModel, load_history
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.telemetry import (
        default_registry,
        prometheus_text,
        save_trace,
        trace_events,
    )
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    def fail(msg):
        print(f"TELEMETRY_SMOKE FAIL: {msg}")
        return 1

    t0 = custom_pre_process_function()
    with faults.injected("nan_grad@step=3"):
        t = Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0, transform=t0),
                      SyntheticCIFAR10(size=32, seed=1, transform=t0)),
            epochs=1, batch_size=16, model_dir=workdir, metric=None,
            lr=0.01, save_history=True, telemetry=True, log_every_steps=1,
            rollback_bad_steps=1,
        )
        t.fit()

    # 1. Registry gauges + flight dump + history.json.
    snap = default_registry().snapshot()
    if snap.get("train_skipped_steps_total", 0) < 1:
        return fail(f"skipped-step counter not published: {snap}")
    if t._train_step._cache_size() != 1:
        return fail(
            f"telemetry caused recompiles: {t._train_step._cache_size()}"
        )
    dumps = [f for f in os.listdir(workdir) if f.startswith("flight_")]
    if not dumps:
        return fail("no flight dump after nan_grad rollback")
    payload = json.load(open(os.path.join(workdir, dumps[0])))
    if payload.get("first_bad_step") != 3:
        return fail(f"flight dump does not name step 3: {payload.get('first_bad_step')}")
    hist = load_history(workdir)
    if hist.get("rollbacks") != 1 or sum(hist.get("skipped_steps", [])) != 1:
        return fail(f"history.json resilience ledger wrong: {hist}")

    # 2. Prometheus text + JSONL sink.
    text = prometheus_text(default_registry())
    if "# TYPE train_grad_norm gauge" not in text:
        return fail("prometheus exposition missing train gauges")
    for line in text.splitlines():
        if not (line.startswith("#") or " " in line):
            return fail(f"malformed exposition line: {line!r}")
    with open(os.environ["ML_TRAINER_TPU_METRICS_JSONL"]) as fp:
        lines = [json.loads(ln) for ln in fp if ln.strip()]
    if not any(ln.get("kind") == "train_step" for ln in lines):
        return fail("JSONL sink holds no train_step events")

    # 3. Spans: the run's host regions are on the trace.
    names = {e["name"] for e in trace_events()}
    for expected in ("data_load", "h2d", "ckpt_write"):
        if expected not in names:
            return fail(f"span {expected!r} missing from trace ({names})")
    trace_path = save_trace(os.path.join(workdir, "trace.json"))
    loaded = json.load(open(trace_path))
    if not loaded.get("traceEvents"):
        return fail("saved Perfetto trace is empty")

    print(
        "TELEMETRY_SMOKE OK: "
        f"{int(snap['train_steps_total'])} steps telemetered, "
        f"flight dump {dumps[0]} names step 3, "
        f"{len(loaded['traceEvents'])} trace events, "
        f"{len(lines)} JSONL records"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
