#!/usr/bin/env python
"""Fault-injection smoke leg (scripts/fastlane.sh) — ~30s on CPU.

One tiny end-to-end pass over the resilience layer's two cheapest
guarantees, as a standalone script so the fast lane exercises the REAL
env-var plumbing (``ML_TRAINER_TPU_FAULTS``), not just the programmatic
test hooks:

1. ``nan_grad`` — the injected NaN step is skipped on-device, counted in
   ``history['skipped_steps']``, and the run finishes finite.
2. ``preempt`` — the injected preemption exits ``fit()`` cleanly with an
   emergency checkpoint + marker, and ``fit(resume=True)`` reproduces the
   uninterrupted run's final params bit-for-bit.

Exits non-zero (with a reason) on any violation.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    import jax

    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    def mk(model_dir, **kw):
        t = custom_pre_process_function()
        return Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=64, seed=0, transform=t),
                      SyntheticCIFAR10(size=32, seed=1, transform=t)),
            epochs=2, batch_size=16, model_dir=model_dir, metric=None,
            lr=0.01, **kw,
        )

    def fail(msg):
        print(f"CHAOS_SMOKE FAIL: {msg}")
        return 1

    # Reference: uninterrupted run.
    ref = mk(tempfile.mkdtemp())
    ref.fit()

    # 1. nan_grad via the env var (the CLI-facing injection path).
    os.environ[faults.ENV_VAR] = "nan_grad@step=3"
    try:
        t = mk(tempfile.mkdtemp())
        t.fit()
    finally:
        del os.environ[faults.ENV_VAR]
    if t.history["skipped_steps"] != [1, 0]:
        return fail(f"nan_grad skip counts {t.history['skipped_steps']}")
    if not all(np.isfinite(v) for v in t.train_losses):
        return fail(f"non-finite history {t.train_losses}")
    if not all(
        np.all(np.isfinite(leaf)) for leaf in jax.tree.leaves(t.state.params)
    ):
        return fail("non-finite params after guarded NaN step")
    print("CHAOS_SMOKE nan_grad: skipped step counted, run finite")

    # 2. preempt mid-epoch-2 + bit-exact resume.
    d = tempfile.mkdtemp()
    with faults.injected("preempt@step=6"):
        t1 = mk(d, save_every_steps=2)
        t1.fit()
    if not t1.preempted:
        return fail("preempt fault did not trip fit()")
    marker = os.path.join(d, "checkpoints", "PREEMPTED.json")
    if not os.path.exists(marker):
        return fail("no clean-exit marker after preemption")
    t2 = mk(d, save_every_steps=2)
    t2.fit(resume=True)
    if t2.history["epochs"] != ref.history["epochs"]:
        return fail(f"resumed epochs {t2.history['epochs']}")
    for a, b in zip(
        jax.tree.leaves(ref.state.params), jax.tree.leaves(t2.state.params)
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return fail("resumed params differ from uninterrupted run")
    print("CHAOS_SMOKE preempt: clean exit, bit-exact mid-epoch resume")
    print("CHAOS_SMOKE PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
