#!/usr/bin/env python
"""Multi-process serving-fleet smoke leg (scripts/fastlane.sh) — the
PR 16 tentpole end to end, with REAL OS processes (serving/fleet.py):

1. A 4-process fleet (2 prefill + 2 decode), every replica its own
   ``python -m ml_trainer_tpu.serving.fleet --worker`` process, the
   router driving them ONLY over HTTP sockets: greedy and seeded-
   sampled outputs byte-identical to in-driver ``generate()``, KV
   migration metered in real socket bytes, chunked prefill engaged on
   the long prompts (``prefill_chunks_total`` on the prefill replicas'
   ``/metrics.json``), distinct worker pids on ``/healthz``.
2. A REAL ``SIGKILL`` mid-stream (no goodbye — the socket severs; the
   router discovers the death via failed health polls and retryable
   stream errors): every in-flight stream redistributes and finishes
   BYTE-IDENTICAL to the uninterrupted reference.
3. The SLO-burn autoscaler's replace-dead repair spawns a REAL
   replacement process (``Fleet.factory``) with a fresh pid, and the
   restored fleet serves byte-identical traffic.

Prints ``FLEET_SMOKE OK`` / ``FLEET_SMOKE FAIL: <why>``; non-zero exit
on any violation.  CPU-only, ~4 worker processes, tiny model.
"""

import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"FLEET_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Autoscaler, AutoscalerConfig
    from ml_trainer_tpu.serving.fleet import Fleet

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    # Long prompts (> prefill_chunk=16) force chunked prefill; short
    # ones ride a single window — both must be byte-identical.
    prompts = [
        np.asarray(rng.integers(0, 1024, n), np.int32)
        for n in (9, 40, 12, 33)
    ]
    refs = [
        np.asarray(generate(model, variables, p[None], 12))[0]
        for p in prompts
    ]
    ref_sampled = np.asarray(
        generate(model, variables, prompts[0][None], 10, temperature=0.7,
                 rng=jax.random.PRNGKey(7))
    )[0]
    long_new = [min(28, 64 - len(p) - 1) for p in prompts]
    long_refs = [
        np.asarray(generate(model, variables, p[None], n))[0]
        for p, n in zip(prompts, long_new)
    ]

    fleet = Fleet(
        roles=["prefill", "prefill", "decode", "decode"],
        model_name="gpt2_tiny", max_len=64, max_batch=2,
        kv_page_size=8, prefill_chunk=16, seed=0,
    )
    fleet.start()
    router = fleet.make_router(hedging=False)
    autoscaler = None
    try:
        # -- leg 1: byte identity through socket migration ------------
        pids = {n: r.pid for n, r in fleet.replicas.items()}
        if len(set(pids.values())) != 4 or os.getpid() in pids.values():
            return fail(f"workers are not distinct processes: {pids}")
        outs = [
            np.asarray(router.complete(p, 12, timeout=300))
            for p in prompts
        ]
        sampled = np.asarray(
            router.complete(prompts[0], 10, temperature=0.7, rng=7,
                            timeout=300)
        )
        for out, ref in zip(outs, refs):
            if not np.array_equal(out, ref):
                return fail("migrated output diverged from generate()")
        if not np.array_equal(sampled, ref_sampled):
            return fail("sampled migrated output diverged")
        snap = router.snapshot()
        if snap["migrations_total"] < len(prompts):
            return fail(
                f"expected socket migrations, got "
                f"{snap['migrations_total']}"
            )
        if snap["kv_migrated_bytes_total"] <= 0:
            return fail("migrated socket bytes not metered")
        chunks = 0
        for name in ("prefill0", "prefill1"):
            with urllib.request.urlopen(
                f"{fleet.replicas[name].url}/metrics.json", timeout=10
            ) as resp:
                m = json.loads(resp.read())
            chunks += int(m.get("prefill_chunks_total", 0))
            h = fleet.replicas[name].health()
            if h.get("transport") != "http" or h.get("pid") != pids[name]:
                return fail(f"worker health pid/transport wrong: {h}")
        if chunks < 2:
            return fail(f"chunked prefill never engaged (chunks={chunks})")
        print(f"# fleet smoke: {len(prompts) + 1} requests "
              f"byte-identical across 4 processes, "
              f"{snap['migrations_total']} socket migration(s) / "
              f"{snap['kv_migrated_bytes_total']} bytes, "
              f"{chunks} prefill chunk(s)")

        # -- leg 2: real SIGKILL mid-stream ----------------------------
        streams = [
            router.submit(p, n) for p, n in zip(prompts, long_new)
        ]
        deadline = time.monotonic() + 120
        while any(len(s.tokens) < 2 for s in streams):
            if time.monotonic() > deadline:
                return fail("streams never started decoding")
            time.sleep(0.02)
        victim = fleet.replicas["decode0"]
        fleet.kill("decode0")  # SIGKILL, no goodbye
        if victim.proc is not None and victim.proc.poll() is None:
            return fail("SIGKILL'd worker still running")
        outs = [np.asarray(s.result(timeout=300)) for s in streams]
        for out, ref in zip(outs, long_refs):
            if not np.array_equal(out, ref):
                return fail("post-SIGKILL stream diverged from reference")
        snap = router.snapshot()
        if snap["redistributes_total"] < 1:
            return fail("SIGKILL produced no redistribution")
        print(f"# fleet smoke: SIGKILL pid {victim.pid} mid-stream -> "
              f"{snap['redistributes_total']} redistribution(s), all "
              f"streams byte-identical")

        # -- leg 3: autoscaler respawns a real process -----------------
        autoscaler = Autoscaler(
            router, fleet.factory,
            AutoscalerConfig(poll_interval_s=0.2, min_prefill=2,
                             min_decode=2, replace_cooldown_s=0.2),
        ).start()
        deadline = time.monotonic() + 180
        new_pid = None
        while time.monotonic() < deadline:
            alive_decode = [
                r for r in router.replicas.values()
                if r.healthy and not r.removing
                and r.role in ("decode", "both")
            ]
            if len(alive_decode) >= 2:
                fresh = [r for r in alive_decode
                         if r.name.startswith("auto")]
                if fresh:
                    new_pid = fresh[0].server.pid
                    break
            time.sleep(0.2)
        if new_pid is None:
            return fail("autoscaler never respawned the dead decode")
        if new_pid == victim.pid or new_pid == os.getpid():
            return fail(f"respawn reused a pid: {new_pid}")
        out = np.asarray(router.complete(prompts[1], 12, timeout=300))
        if not np.array_equal(out, refs[1]):
            return fail("restored fleet output diverged")
        actions = [a["action"] for a in autoscaler.actions]
        if "scale_up" not in actions:
            return fail(f"no scale_up action recorded: {actions}")
        print(f"# fleet smoke: autoscaler respawned decode as pid "
              f"{new_pid}, restored fleet byte-identical")
    finally:
        if autoscaler is not None:
            autoscaler.close()
        router.close()
        fleet.stop()
    print("FLEET_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
