#!/usr/bin/env python
"""Fastlane smoke: the Pallas kernel layer (ml_trainer_tpu/ops/kernels/).

A 2-virtual-device dryrun over the three fused kernels and their
engine/trainer wiring, asserting the acceptance invariants end to end:

1. **Interpret parity** (hard, bitwise): each Pallas kernel run in
   interpret mode equals its lax reference bit-for-bit on CPU —
   paged-attention decode at fp32 AND bf16 over ragged lengths (full
   row / length-1 trash-page row / partial last page), the fused
   unscale+sqsum and Adam-tail update over 1-d/2-d/3-d leaves, and the
   int8 weight-quantized matmul.
2. **Engine byte identity + zero recompiles**: the REAL ``Server`` run
   twice over ragged traffic — gather+flash vs ``paged_kernel=True`` —
   streams identical bytes; a steady-state decode loop after
   ``compile_watch.mark_warm()`` compiles NOTHING.
3. **Trainer golden**: ``dp_update='sharded'`` + ``optimizer='adam'``
   auto-enables the fused tail; fused and unfused trainers produce
   bit-identical losses AND params over a 2-device mesh, one compiled
   program each.
4. **Structured refusals**: ``paged_kernel`` without paged KV,
   ``quant_int8`` with spec_k / adapters, and ``fused_adam=True`` on
   ineligible configs all raise ValueError up front — never a silent
   fallback.
5. **Int8 quality gate**: a gpt2_tiny briefly trained on a
   deterministic successor map (peaked logits — real top-1 margins,
   unlike random-token targets) served quantized agrees with fp32 on
   >= 99.5% of argmaxes with bounded relative logit error.

Prints one ``KERNELS_SMOKE_RESULT {json}`` line then
``KERNELS_SMOKE_OK``.  Exits non-zero with a reason on any violation.
Runs on CPU in ~2 min.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

AGREEMENT_FLOOR = 0.995
REL_ERR_CEIL = 0.02


def main() -> int:
    import jax.numpy as jnp
    import numpy as np

    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data.datasets import ArrayDataset
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops.kernels import (
        fused_adam_update,
        int8_matmul,
        paged_attention,
        paged_attention_reference,
        quantize_per_channel,
        quantize_tree,
        unscale_sqsum,
    )
    from ml_trainer_tpu.serving.api import Server
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine
    from ml_trainer_tpu.telemetry import compile_watch

    def fail(msg):
        print(f"KERNELS_SMOKE FAIL: {msg}")
        return 1

    def bits_equal(a, b):
        return all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    def jrun(fn, *a, **kw):
        # Parity holds under jit on both sides — the mode every caller
        # runs in.  Eager reference vs traced kernel differs by FMA
        # fusion noise, which no real path ever sees.
        return jax.jit(lambda *args: fn(*args, **kw))(*a)

    assert jax.device_count() >= 2, "2-virtual-device mesh not active"
    result = {"backend": jax.default_backend()}
    rng = np.random.default_rng(0)

    # ---- leg 1: interpret parity, kernel == reference bit-for-bit ------
    b, h, d, ps, P = 3, 2, 16, 8, 3
    n_pages = b * P + 1  # + trash page 0
    table = jnp.asarray(
        1 + rng.permutation(n_pages - 1).reshape(b, P), jnp.int32
    )
    # Full row / length-1 (everything past token 0 is trash-page reads
    # that the mask must kill) / partial last page.
    lengths = jnp.asarray([ps * P, 1, ps * 2 + 1], jnp.int32)
    for dtype in (jnp.float32, jnp.bfloat16):
        q = jnp.asarray(rng.normal(size=(b, h, d)) * 0.5, dtype)
        kp, vp = (
            jnp.asarray(rng.normal(size=(n_pages, h, ps, d)) * 0.5, dtype)
            for _ in range(2)
        )
        got = jrun(paged_attention, q, kp, vp, table, lengths,
                   implementation="pallas", interpret=True)
        want = jrun(paged_attention_reference, q, kp, vp, table, lengths)
        if not bits_equal(got, want):
            return fail(f"paged_attention interpret parity broken at "
                        f"{np.dtype(dtype).name}")
    for shape in ((64,), (8, 16), (4, 4, 8)):
        g = jnp.asarray(rng.normal(size=shape), jnp.float32)
        got = jrun(unscale_sqsum, g, 2.0, implementation="pallas",
                   interpret=True)
        want = jrun(unscale_sqsum, g, 2.0, implementation="reference")
        if not bits_equal(got, want):
            return fail(f"unscale_sqsum interpret parity broken at "
                        f"{shape}")
        p, mu = (
            jnp.asarray(rng.normal(size=shape), jnp.float32)
            for _ in range(2)
        )
        nu = jnp.abs(jnp.asarray(rng.normal(size=shape), jnp.float32))
        scal = dict(
            bc1=jnp.float32(1.0 - 0.9 ** 2),
            bc2=jnp.float32(1.0 - 0.999 ** 2),
            step_size=jnp.float32(1e-3), lr_scale=jnp.float32(1.0),
            factor=jnp.float32(0.5),
        )
        got = jrun(fused_adam_update, g, p, mu, nu,
                   implementation="pallas", interpret=True, **scal)
        want = jrun(fused_adam_update, g, p, mu, nu,
                    implementation="reference", **scal)
        # The STATE (p', mu', nu') pins bitwise; u is the telemetry
        # update-norm input only — XLA may fuse its final multiplies
        # differently across the two programs (1-ulp noise), and it
        # never feeds the trajectory.
        if not bits_equal(got[:3], want[:3]):
            return fail(f"fused_adam_update interpret parity broken at "
                        f"{shape}")
        if not np.allclose(np.asarray(got[3]), np.asarray(want[3]),
                           rtol=1e-5, atol=1e-9):
            return fail(f"fused_adam_update telemetry update diverged "
                        f"at {shape}")
    x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    w_q, scale = quantize_per_channel(
        jnp.asarray(rng.normal(size=(32, 48)), jnp.float32)
    )
    if not bits_equal(
        jrun(int8_matmul, x, w_q, scale, implementation="pallas",
             interpret=True),
        jrun(int8_matmul, x, w_q, scale, implementation="reference"),
    ):
        return fail("int8_matmul interpret parity broken")
    result["interpret_parity"] = True
    print("# kernels smoke: interpret parity (3 kernels, fp32+bf16 "
          "paged) OK")

    # ---- leg 2: real Server byte identity + zero-recompile pin ---------
    compile_watch.install()
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    prompts = [
        np.asarray(rng.integers(0, 1024, ln), np.int32)
        for ln in (5, 3, 12, 7, 17, 9)
    ]

    def run_requests(paged_kernel):
        outs = []
        with Server(model, variables, max_batch=4, kv_page_size=16,
                    paged_kernel=paged_kernel) as server:
            streams = [
                server.submit(p, 12, temperature=0.7, rng=42)
                if i == 3 else server.submit(p, 12)
                for i, p in enumerate(prompts)
            ]
            for s in streams:
                outs.append(np.asarray(s.result(timeout=600)))
        return outs

    if not all(
        np.array_equal(a, bb)
        for a, bb in zip(run_requests(False), run_requests(True))
    ):
        return fail("paged_kernel engine is not byte-identical to the "
                    "gather engine")
    eng = SlotDecodeEngine(model, variables, max_batch=4,
                           kv_page_size=16, paged_kernel=True)
    cache, tok = eng.cache, eng.tok
    for _ in range(3):  # warmup: build the decode program
        cache, tok = eng._decode(
            eng.params, cache, tok, eng._temps, eng._rngs, eng._steps
        )
    jax.block_until_ready(tok)
    compile_watch.mark_warm()
    for _ in range(8):
        cache, tok = eng._decode(
            eng.params, cache, tok, eng._temps, eng._rngs, eng._steps
        )
    jax.block_until_ready(tok)
    post = compile_watch.post_warmup_count()
    compile_watch.mark_cold()  # the trainer legs compile on purpose
    if post:
        return fail(
            f"{post} post-warmup recompile(s) in the paged decode loop: "
            f"{[e.as_dict() for e in compile_watch.events(last=4)]}"
        )
    result["decode"] = {"byte_identical": True, "post_warmup_compiles": 0}
    print("# kernels smoke: Server byte identity + zero post-warmup "
          "compiles OK")

    # ---- leg 3: trainer golden, fused tail == optax bit-for-bit --------
    from ml_trainer_tpu.data import SyntheticTokens

    workdir = tempfile.mkdtemp(prefix="kernels_smoke_")
    ds = SyntheticTokens(size=64, seq_len=32, vocab_size=256, seed=0)
    common = dict(
        datasets=(ds, ds), epochs=2, batch_size=16, seed=3, lr=0.01,
        optimizer="adam", metric=None, is_parallel=True, backend="cpu",
        dp_update="sharded",
    )
    t_ref = Trainer(
        get_model("gpt2_tiny", vocab_size=256), fused_adam=False,
        model_dir=os.path.join(workdir, "ref"), **common,
    )
    t_ref.fit()
    t_fused = Trainer(
        get_model("gpt2_tiny", vocab_size=256),
        model_dir=os.path.join(workdir, "fused"), **common,
    )
    if not t_fused.fused_adam:
        return fail("sharded+adam did not auto-enable fused_adam")
    t_fused.fit()
    if t_fused._train_step._cache_size() != 1:
        return fail("fused trainer compiled more than one train step")
    if t_ref.train_losses != t_fused.train_losses:
        return fail(
            f"fused trajectory diverged: {t_ref.train_losses} vs "
            f"{t_fused.train_losses}"
        )
    if not bits_equal(t_ref.state.params, t_fused.state.params):
        return fail("fused params differ bitwise from the optax tail")
    result["fused_adam"] = {
        "trajectory_bitwise": True,
        "final_loss": float(t_fused.train_losses[-1]),
    }
    print("# kernels smoke: fused-vs-optax sharded Adam bit-identical OK")

    # ---- leg 4: structured refusals ------------------------------------
    refusals = []
    for label, ctor in (
        ("paged_kernel_without_paged_kv", lambda: SlotDecodeEngine(
            model, variables, max_batch=2, paged_kernel=True)),
        ("quant_int8_with_spec_k", lambda: SlotDecodeEngine(
            model, variables, max_batch=2, kv_page_size=16,
            quant_int8=True, spec_k=2)),
        ("quant_int8_with_adapters", lambda: SlotDecodeEngine(
            model, variables, max_batch=2, kv_page_size=16,
            quant_int8=True, adapters=object())),
        ("fused_adam_needs_sharded", lambda: Trainer(
            get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds),
            model_dir=os.path.join(workdir, "r1"), fused_adam=True,
            epochs=1, batch_size=16, optimizer="adam", metric=None,
            backend="cpu")),
        ("fused_adam_needs_adam", lambda: Trainer(
            get_model("gpt2_tiny", vocab_size=256), datasets=(ds, ds),
            model_dir=os.path.join(workdir, "r2"), fused_adam=True,
            epochs=1, batch_size=16, optimizer="adamw", metric=None,
            is_parallel=True, backend="cpu", dp_update="sharded")),
    ):
        try:
            ctor()
            return fail(f"{label}: expected ValueError, got none")
        except ValueError as e:
            refusals.append({"case": label, "error": str(e)[:80]})
    result["refusals"] = refusals
    print(f"# kernels smoke: {len(refusals)} structured refusals OK")

    # ---- leg 5: int8 quality gate on a peaked-logit model --------------
    # Random next-token targets leave logits near-tied (int8 noise flips
    # argmax at random); a deterministic successor map is memorized in a
    # few epochs, so fp32 top-1 margins dwarf the quantization error and
    # agreement measures the kernel, not the tie-breaking.
    V, S, N = 64, 32, 64
    succ = rng.permutation(V)
    data = np.zeros((N, S), np.int32)
    data[:, 0] = rng.integers(0, V, N)
    for t in range(1, S):
        data[:, t] = succ[data[:, t - 1]]
    qmodel = get_model("gpt2_tiny", vocab_size=V)
    tq = Trainer(
        qmodel, datasets=(
            ArrayDataset(data, np.roll(data, -1, axis=1), None),
        ) * 2,
        model_dir=os.path.join(workdir, "quality"), epochs=4,
        batch_size=16, seed=3, lr=0.01, optimizer="adamw", metric=None,
        backend="cpu",
    )
    tq.fit()
    params = tq.state.params
    toks = jnp.asarray(data[:8])
    lf = qmodel.apply({"params": params}, toks, train=False)
    lq = qmodel.clone(quant_int8=True).apply(
        {"params": params, "quant": quantize_tree(params)}, toks,
        train=False,
    )
    agreement = float((jnp.argmax(lf, -1) == jnp.argmax(lq, -1)).mean())
    rel_err = float(jnp.max(jnp.abs(lf - lq)) / jnp.max(jnp.abs(lf)))
    result["int8_quality"] = {
        "argmax_agreement": round(agreement, 4),
        "max_rel_logit_err": round(rel_err, 5),
        "final_loss": float(tq.train_losses[-1]),
    }
    if agreement < AGREEMENT_FLOOR:
        return fail(
            f"int8 argmax agreement {agreement:.4f} < {AGREEMENT_FLOOR}"
        )
    if rel_err > REL_ERR_CEIL:
        return fail(f"int8 relative logit error {rel_err:.5f} > "
                    f"{REL_ERR_CEIL}")
    print(f"# kernels smoke: int8 quality agreement={agreement:.4f} "
          f"rel_err={rel_err:.5f} OK")

    print("KERNELS_SMOKE_RESULT " + json.dumps(result))
    print(
        "KERNELS_SMOKE_OK: interpret parity x3, byte-identical paged "
        "decode (0 post-warmup compiles), bit-identical fused Adam, "
        f"{len(refusals)} refusals, int8 agreement {agreement:.4f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
