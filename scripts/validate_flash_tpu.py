"""Validate the Pallas flash-attention kernels on REAL TPU hardware.

Closes the round-1 gap "flash kernel has no TPU validation on record"
(tests exercise interpret mode only): runs the Mosaic-compiled forward and
backward kernels on the chip, checks them against the XLA
dot_product_attention path (values + all three input grads), and times
both.  Writes a JSON record to docs/flash_tpu_validation.json so the
result is committed evidence, not a claim.

    python scripts/validate_flash_tpu.py
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from ml_trainer_tpu.ops.attention import dot_product_attention, flash_attention  # noqa: E402


def bench(fn, *args, iters=20):
    from ml_trainer_tpu.utils.profiler import force

    # Iterations must be DATA-DEPENDENT: on this platform in-order stream
    # scheduling cannot be assumed (the observation behind force()), so
    # fencing only the last of N independent calls would not prove the
    # other N-1 ran inside the window.  A lax.scan threading one output
    # element back into the next iteration's input chains every call
    # inside ONE compiled program — provably-complete timing with a single
    # dispatch (per-op eager chaining would pay one tunnel round trip per
    # link and measure dispatch, not kernels).
    @jax.jit
    def run_n(first, *rest):
        def body(carry, _):
            out = fn(carry, *rest)
            leaf = jnp.ravel(jax.tree.leaves(out)[0])[0]
            return first + (leaf * 0).astype(first.dtype), None

        carry, _ = jax.lax.scan(body, first, None, length=iters)
        return carry

    force(run_n(*args))  # compile + warm
    t0 = time.perf_counter()
    force(run_n(*args))
    return (time.perf_counter() - t0) / iters


def main():
    import time

    from ml_trainer_tpu.utils.tunnel import acquire_tunnel_lock

    if not acquire_tunnel_lock(time.time() + 300.0, [],
                               label="validate_flash_tpu.py"):
        sys.exit("tunnel lock held by another client; try again later")
    assert jax.default_backend() == "tpu", (
        f"needs the real TPU, got {jax.default_backend()}"
    )
    record = {"device": str(jax.devices()[0]), "cases": []}
    rng = np.random.default_rng(0)
    for (b, h, s, d), causal in [
        ((2, 4, 512, 64), False),
        ((2, 4, 512, 64), True),
        ((1, 12, 2048, 64), True),   # GPT-2-ish long context
    ]:
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, jnp.float32)
            for _ in range(3)
        )

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, None, causal).sum()

        def loss_xla(q, k, v):
            return dot_product_attention(q, k, v, causal=causal).sum()

        f_fwd = jax.jit(lambda q, k, v: flash_attention(q, k, v, None, causal))
        x_fwd = jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=causal))
        f_grad = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
        x_grad = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))

        # On TPU the default f32 matmul runs in bf16 passes, so BOTH
        # implementations carry a precision noise floor that grows with S.
        # The honest reference is the XLA path traced under
        # float32-precision matmuls; flash passes if its error against
        # that reference is within a small factor of default-XLA's own —
        # i.e. flash is no less accurate than the baseline it replaces,
        # rather than holding flash to a threshold the baseline itself
        # cannot meet at long S.
        with jax.default_matmul_precision("float32"):
            ref_fwd = jax.jit(
                lambda q, k, v: dot_product_attention(q, k, v, causal=causal)
            )
            ref = ref_fwd(q, k, v)
            gref = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))(q, k, v)
        of, ox = f_fwd(q, k, v), x_fwd(q, k, v)
        fwd_err = float(jnp.max(jnp.abs(of - ref)))
        fwd_err_xla = float(jnp.max(jnp.abs(ox - ref)))
        gf, gx = f_grad(q, k, v), x_grad(q, k, v)
        grad_err = float(
            max(jnp.max(jnp.abs(a - b)) for a, b in zip(gf, gref))
        )
        grad_err_xla = float(
            max(jnp.max(jnp.abs(a - b)) for a, b in zip(gx, gref))
        )
        t_f = bench(f_fwd, q, k, v)
        t_x = bench(x_fwd, q, k, v)
        t_fg = bench(f_grad, q, k, v)
        t_xg = bench(x_grad, q, k, v)
        case = {
            "shape": [b, h, s, d], "causal": causal,
            "fwd_max_abs_err": fwd_err, "grad_max_abs_err": grad_err,
            "fwd_max_abs_err_xla_default": fwd_err_xla,
            "grad_max_abs_err_xla_default": grad_err_xla,
            "fwd_ms": {"flash": round(t_f * 1e3, 3), "xla": round(t_x * 1e3, 3)},
            "grad_ms": {"flash": round(t_fg * 1e3, 3), "xla": round(t_xg * 1e3, 3)},
            "pass": (
                fwd_err < max(2e-3, 3 * fwd_err_xla)
                and grad_err < max(2e-2, 3 * grad_err_xla)
            ),
        }
        record["cases"].append(case)
        print(case, flush=True)
    # Right-padded (kv_lens) path: BERT's inference mask family, fused in
    # the kernel — validated against the XLA path under the equivalent
    # boolean key mask (values + all three grads).
    b, h, s, d = 2, 4, 512, 64
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, jnp.float32)
        for _ in range(3)
    )
    kv_lens = jnp.asarray([s, 200], jnp.int32)
    bool_mask = (
        jnp.arange(s)[None, None, None, :] < kv_lens[:, None, None, None]
    )

    def loss_flash_pad(q, k, v):
        return flash_attention(q, k, v, kv_lens, False).sum()

    def loss_xla_pad(q, k, v):
        return dot_product_attention(q, k, v, mask=bool_mask).sum()

    of = jax.jit(lambda q, k, v: flash_attention(q, k, v, kv_lens, False))(
        q, k, v
    )
    ox = jax.jit(
        lambda q, k, v: dot_product_attention(q, k, v, mask=bool_mask)
    )(q, k, v)
    gf = jax.jit(jax.grad(loss_flash_pad, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_xla_pad, argnums=(0, 1, 2)))(q, k, v)
    case = {
        "shape": [b, h, s, d], "kv_lens": [int(x) for x in kv_lens],
        "fwd_max_abs_err": float(jnp.max(jnp.abs(of - ox))),
        "grad_max_abs_err": float(
            max(jnp.max(jnp.abs(a - b_)) for a, b_ in zip(gf, gx))
        ),
    }
    case["pass"] = (
        case["fwd_max_abs_err"] < 2e-3 and case["grad_max_abs_err"] < 2e-2
    )
    record["cases"].append(case)
    print(case, flush=True)

    # Off-tile shapes through the padding wrapper (ViT-like S=197, head
    # dim not a multiple of 64) — the Mosaic-compiled padded path must
    # match the XLA path on values and grads.
    from ml_trainer_tpu.ops.attention import _flash_padded

    b, h, s, d = 2, 3, 197, 48
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, jnp.float32)
        for _ in range(3)
    )

    def loss_flash_off(q, k, v):
        return _flash_padded(q, k, v, None, True, None, 128, 128).sum()

    def loss_xla_off(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    of = jax.jit(
        lambda q, k, v: _flash_padded(q, k, v, None, True, None, 128, 128)
    )(q, k, v)
    ox = jax.jit(
        lambda q, k, v: dot_product_attention(q, k, v, causal=True)
    )(q, k, v)
    gf = jax.jit(jax.grad(loss_flash_off, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_xla_off, argnums=(0, 1, 2)))(q, k, v)
    # Same noise-floor methodology as the dense cases above: measure both
    # implementations against the float32-precision XLA reference.
    with jax.default_matmul_precision("float32"):
        ref = jax.jit(
            lambda q, k, v: dot_product_attention(q, k, v, causal=True)
        )(q, k, v)
        gref = jax.jit(jax.grad(loss_xla_off, argnums=(0, 1, 2)))(q, k, v)
    fwd_err = float(jnp.max(jnp.abs(of - ref)))
    fwd_err_xla = float(jnp.max(jnp.abs(ox - ref)))
    grad_err = float(max(jnp.max(jnp.abs(a - b_)) for a, b_ in zip(gf, gref)))
    grad_err_xla = float(
        max(jnp.max(jnp.abs(a - b_)) for a, b_ in zip(gx, gref))
    )
    case = {
        "shape": [b, h, s, d], "padded": True, "causal": True,
        "fwd_max_abs_err": fwd_err, "grad_max_abs_err": grad_err,
        "fwd_max_abs_err_xla_default": fwd_err_xla,
        "grad_max_abs_err_xla_default": grad_err_xla,
    }
    case["pass"] = (
        fwd_err < max(2e-3, 3 * fwd_err_xla)
        and grad_err < max(2e-2, 3 * grad_err_xla)
    )
    record["cases"].append(case)
    print(case, flush=True)

    # bf16 — the dtype every north-star model actually trains in.  The
    # kernel accumulates in f32 (scores and (o, m, l) scratch), so the
    # only bf16-specific error is the input/output rounding; tolerance
    # scales accordingly.
    b, h, s, d = 2, 4, 1024, 64
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, jnp.bfloat16)
        for _ in range(3)
    )

    def loss_flash_bf16(q, k, v):
        return flash_attention(q, k, v, None, True).sum().astype(jnp.float32)

    def loss_xla_bf16(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum().astype(
            jnp.float32
        )

    of = jax.jit(lambda q, k, v: flash_attention(q, k, v, None, True))(q, k, v)
    ox = jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True))(
        q, k, v
    )
    gf = jax.jit(jax.grad(loss_flash_bf16, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_xla_bf16, argnums=(0, 1, 2)))(q, k, v)
    to_f32 = lambda t: jnp.asarray(t, jnp.float32)  # noqa: E731
    case = {
        "shape": [b, h, s, d], "dtype": "bfloat16", "causal": True,
        "fwd_max_abs_err": float(jnp.max(jnp.abs(to_f32(of) - to_f32(ox)))),
        "grad_max_abs_err": float(
            max(
                jnp.max(jnp.abs(to_f32(a) - to_f32(b_)))
                for a, b_ in zip(gf, gx)
            )
        ),
    }
    # bf16 has ~8 bits of mantissa; two implementations summing ~1K terms
    # in different orders legitimately differ by a few ULPs of the output.
    case["pass"] = (
        case["fwd_max_abs_err"] < 3e-2 and case["grad_max_abs_err"] < 3e-1
    )
    record["cases"].append(case)
    print(case, flush=True)

    # XLA-vs-flash crossover for OFF-TILE sequence lengths: the evidence
    # behind _AUTO_PAD_MIN_SEQ (ops/attention.py).  Each length is one
    # block-boundary + 1, the worst padding ratio for the flash path; the
    # table records fwd+grad time per step for both paths so the auto-pad
    # threshold is a measured choice, not a guess.
    crossover = []
    for s in (129, 257, 513, 1025, 2049):
        b, h, d = 2, 4, 48  # off-tile head dim too: always the padded path
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, jnp.bfloat16)
            for _ in range(3)
        )

        def loss_pad(q, k, v):
            return _flash_padded(
                q, k, v, None, True, None, 128, 128
            ).sum().astype(jnp.float32)

        def loss_x(q, k, v):
            return dot_product_attention(q, k, v, causal=True).sum().astype(
                jnp.float32
            )

        g_pad = jax.jit(jax.grad(loss_pad, argnums=(0, 1, 2)))
        g_x = jax.jit(jax.grad(loss_x, argnums=(0, 1, 2)))
        row = {
            "seq": s,
            "grad_ms": {
                "flash_padded": round(bench(g_pad, q, k, v) * 1e3, 3),
                "xla": round(bench(g_x, q, k, v) * 1e3, 3),
            },
        }
        row["flash_wins"] = (
            row["grad_ms"]["flash_padded"] < row["grad_ms"]["xla"]
        )
        crossover.append(row)
        print(row, flush=True)
    record["auto_pad_crossover"] = crossover

    record["all_pass"] = all(c["pass"] for c in record["cases"])
    out = os.path.join(ROOT, "docs", "flash_tpu_validation.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"-> {out}  all_pass={record['all_pass']}")
    sys.exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
