#!/usr/bin/env python
"""Fastlane smoke: 1F1B + interleaved pipeline schedules end to end.

A 2-virtual-device ``stage`` mesh dryrun through the REAL Trainer —
``gpt2_pipe_tiny`` with ``pipeline_schedule='1f1b'`` and with the
interleaved schedule (2 virtual stages per device) — asserting the
invariants the tentpole promises:

* every schedule's training trajectory equals the serial fold of the
  SAME module on one device (losses rtol 1e-3 — the existing
  trajectory-equality discipline);
* ZERO recompiles per schedule (one compiled train step after two
  epochs of traffic);
* per-hop comm accounting landed in the registry
  (``comm_hop_bytes_total{schedule=,hop=}``) and the analytic bubble
  gauge (``train_pipeline_bubble_fraction{schedule=}``) is live;
* the raw engine agrees with the serial fold on value AND grad for both
  schedules at S=2 (including the zb split-backward variant).

Runs on CPU in under a minute; exits non-zero on any violation.
"""

import os
import sys
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel import create_mesh, rules_for
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_hop_bytes,
        reset_comm_stats,
    )
    from ml_trainer_tpu.parallel.pipeline import (
        pipeline_apply,
        pipeline_schedule_info,
        stack_stage_params,
    )
    from ml_trainer_tpu.telemetry.registry import default_registry

    assert jax.device_count() >= 2, "2-virtual-device mesh not active"
    workdir = tempfile.mkdtemp(prefix="pipeline_smoke_")
    ds = SyntheticTokens(size=32, seq_len=32, vocab_size=256, seed=0)
    common = dict(epochs=2, batch_size=8, seed=3, lr=0.01,
                  optimizer="adamw", metric=None)

    # The serial fold: the SAME module folding its stacked params on one
    # device — every schedule must reproduce this trajectory.
    t_serial = Trainer(
        get_model("gpt2_pipe_tiny", n_stages=2, num_heads=2),
        datasets=(ds, ds), model_dir=os.path.join(workdir, "serial"),
        **common,
    )
    t_serial.fit()

    for sched, n_virtual in (("1f1b", 1), ("interleaved", 2)):
        reset_comm_stats()
        mesh = create_mesh({"stage": 2}, devices=jax.devices()[:2])
        model = get_model(
            "gpt2_pipe_tiny", n_stages=2 * n_virtual, num_heads=2,
            mesh=mesh, n_microbatches=4, n_virtual=n_virtual,
        )
        t_serial_ref = t_serial
        if n_virtual > 1:
            # 4 stages interleaved over 2 devices: its own serial fold.
            t_serial_ref = Trainer(
                get_model("gpt2_pipe_tiny", n_stages=4, num_heads=2),
                datasets=(ds, ds),
                model_dir=os.path.join(workdir, "serial4"), **common,
            )
            t_serial_ref.fit()
        t_pp = Trainer(
            model, datasets=(ds, ds),
            model_dir=os.path.join(workdir, sched),
            mesh_shape={"stage": 2},
            sharding_rules=rules_for("gpt2", "pp"),
            pipeline_schedule=sched, telemetry=True, log_every_steps=2,
            **common,
        )
        t_pp.fit()
        np.testing.assert_allclose(
            t_serial_ref.train_losses, t_pp.train_losses, rtol=1e-3,
            err_msg=f"{sched} trajectory diverged from the serial fold",
        )
        # The real recompile instrument (telemetry/compile_watch.py):
        # nothing compiled after each fit's first epoch closed warmup.
        from ml_trainer_tpu.telemetry import compile_watch

        assert compile_watch.post_warmup_count() == 0, (
            f"{sched} train step recompiled: "
            f"{[e.as_dict() for e in compile_watch.events(last=4)]}"
        )
        hops = comm_hop_bytes().get(sched, {})
        assert "fwd" in hops and "bwd" in hops and (
            "output_broadcast" in hops
        ), hops
        info = pipeline_schedule_info()[sched]
        snap = default_registry().snapshot()
        key = f"train_pipeline_bubble_fraction{{schedule={sched}}}"
        assert abs(snap.get(key, -1) - info["bubble_fraction"]) < 1e-9, (
            key, snap.get(key), info,
        )
        print(f"# pipeline smoke: {sched} losses={t_pp.train_losses} "
              f"bubble={info['bubble_fraction']} hops={sorted(hops)} OK")

    # Raw engine agreement (value AND grad) for all engine schedules at
    # S=2, including the zb split backward.
    mesh = create_mesh({"stage": 2}, devices=jax.devices()[:2])
    rng = np.random.default_rng(0)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    stacked = stack_stage_params([
        {"w": jnp.asarray(rng.normal(0, 0.5, (16, 16)), jnp.float32)}
        for _ in range(2)
    ])
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)

    def serial_loss(p):
        out, _ = jax.lax.scan(
            lambda c, pv: (stage_fn(pv, c), None), x, p
        )
        return jnp.sum(out ** 2)

    vs, gs = jax.value_and_grad(serial_loss)(stacked)
    for sched in ("1f1b", "zb"):
        for remat in (False, True):
            v, g = jax.jit(jax.value_and_grad(
                lambda p: jnp.sum(pipeline_apply(
                    stage_fn, p, x, mesh, n_microbatches=4,
                    schedule=sched, remat=remat) ** 2)
            ))(stacked)
            np.testing.assert_allclose(float(v), float(vs), rtol=1e-5)
            for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gs)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
                )
    print("# pipeline smoke: raw engine value+grad == serial fold "
          "(1f1b/zb x remat) OK")
    print("PIPELINE_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
