#!/usr/bin/env python
"""Disaggregated-router smoke leg (scripts/fastlane.sh) — ~90s on CPU.

One short end-to-end pass over the router + KV-migration stack
(serving/router.py, transfer.py), on a 2-replica in-process router:

1. **Byte identity through migration.**  Requests routed
   prefill -> page-granular KV migrate -> decode reproduce standalone
   ``generate()`` outputs byte-for-byte (greedy and seeded sampling),
   with real migrations counted and metered in bytes.
2. **Routing surfaces.**  The router HTTP front end serves
   ``/v1/generate`` (with sessions), and the ``/metrics`` scrape
   carries the ``router_requests_total{role=,replica=}``,
   ``router_kv_migrated_bytes_total``, ``router_replica_healthy`` and
   per-replica SLO attainment series; replica ``/healthz`` exposes the
   placement fields (role, queue_depth, kv_pages_free, active_slots).
3. **Stickiness.**  A session pins its decode placement to one replica.
4. **Replica-kill drain-and-redistribute.**  A decode replica dies
   mid-stream: in-flight requests redistribute to a survivor with their
   committed tokens as a resumable prefix and finish byte-identically;
   with the redistribution budget at zero the client instead gets a
   STRUCTURED error (never a hang).

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"ROUTER_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Router

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    prompts = [
        np.asarray(rng.integers(0, 1024, n), np.int32)
        for n in (9, 6, 12, 8)
    ]
    refs = [
        np.asarray(generate(model, variables, p[None], 12))[0]
        for p in prompts
    ]
    ref_sampled = np.asarray(
        generate(model, variables, prompts[0][None], 10, temperature=0.7,
                 rng=jax.random.PRNGKey(7))
    )[0]

    # 1+2+3: 2-replica disaggregated router, driven over HTTP.
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=8) as router:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"
        outs = []
        for i, p in enumerate(prompts):
            body = json.dumps({
                "prompt": [int(t) for t in p], "max_new_tokens": 12,
                "tenant": f"t{i % 2}", "session": "chat-0",
            }).encode()
            req = urllib.request.Request(
                f"{url}/v1/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                outs.append(np.asarray(
                    json.loads(resp.read())["tokens"], np.int32
                ))
        sampled = np.asarray(
            router.complete(prompts[0], 10, temperature=0.7, rng=7,
                            timeout=300)
        )
        with urllib.request.urlopen(f"{url}/metrics", timeout=30) as resp:
            prom = resp.read().decode()
        with urllib.request.urlopen(f"{url}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        snap = router.snapshot()
        rep_health = router.replica("decode0").fetch_health()
    for out, ref in zip(outs, refs):
        if not np.array_equal(out, ref):
            return fail("migrated output diverged from generate()")
    if not np.array_equal(sampled, ref_sampled):
        return fail("sampled migrated output diverged from generate()")
    if snap["migrations_total"] < len(prompts) + 1:
        return fail(f"expected migrations, got {snap['migrations_total']}")
    if snap["kv_migrated_bytes_total"] <= 0:
        return fail("migrated bytes not metered")
    for needle in (
        'router_requests_total{',
        "router_kv_migrated_bytes_total",
        'router_replica_healthy{replica="decode0"} 1',
        'router_replica_slo_attainment{',
        "router_redistributes_total",
        "router_migrations_total",
    ):
        if needle not in prom:
            return fail(f"{needle!r} missing from /metrics scrape")
    if not health["ok"] or health["mode"] != "disagg":
        return fail(f"router /healthz wrong: {health}")
    for field in ("role", "queue_depth", "kv_pages_free", "active_slots"):
        if field not in rep_health:
            return fail(f"replica /healthz missing {field}")
    decode_placed = {
        k: v for k, v in snap["requests_total"].items()
        if k.startswith("decode/")
    }
    if len(decode_placed) != 1:
        return fail(f"session stickiness broken: {decode_placed}")
    print(f"# router smoke: {len(prompts) + 1} requests byte-identical "
          f"through {snap['migrations_total']} migration(s), "
          f"{snap['kv_migrated_bytes_total']} bytes moved")

    # 4a: replica kill mid-stream -> drain-and-redistribute, outputs
    # still byte-identical.
    long_refs = [
        np.asarray(generate(model, variables, p[None], 28))[0]
        for p in prompts
    ]
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=8) as router:
        streams = [router.submit(p, 28) for p in prompts]
        deadline = time.monotonic() + 120
        while any(len(s.tokens) < 2 for s in streams):
            if time.monotonic() > deadline:
                return fail("streams never started decoding")
            time.sleep(0.02)
        router.kill_replica("decode0")
        outs = [np.asarray(s.result(timeout=300)) for s in streams]
        snap = router.snapshot()
    for out, ref in zip(outs, long_refs):
        if not np.array_equal(out, ref):
            return fail("redistributed output diverged from generate()")
    if snap["redistributes_total"] < 1:
        return fail("kill produced no redistribution")
    if snap["replica_healthy"]["decode0"] != 0:
        return fail("killed replica still marked healthy")
    print(f"# router smoke: replica kill redistributed "
          f"{snap['redistributes_total']} request(s), all byte-identical")

    # 4b: past the redistribution budget the error is STRUCTURED.
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=8,
                      router_kwargs={"max_redistributes": 0,
                                     "admission_retry_s": 2.0},
                      ) as router:
        s = router.submit(prompts[0], 40)
        deadline = time.monotonic() + 120
        while len(s.tokens) < 2:
            if time.monotonic() > deadline:
                return fail("budget leg: stream never started")
            time.sleep(0.02)
        router.kill_replica("decode0")
        try:
            s.result(timeout=300)
            return fail("exhausted redistribution budget did not error")
        except RuntimeError as e:
            msg = str(e)
            if "max_redistributes" not in msg:
                return fail(f"error not structured: {msg}")
    print("# router smoke: redistribution budget exhaustion is a "
          "structured client error")
    print("ROUTER_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
