#!/usr/bin/env python
"""Elastic-training chaos smoke leg (scripts/fastlane.sh) — the ROADMAP
item #1 success metric, end to end: kill one of N simulated hosts
mid-run and the job finishes with a bit-exact-resumable history and
bounded steps-lost (resilience/elastic.py, docs/resilience.md).

Two legs, each phase a fresh subprocess so device counts can differ:

1. **In-process reshape** (the drain→reshape→continue controller): an
   8-device simulated 2-host cluster loses host 1 to a deterministic
   ``host_kill`` fault mid-epoch; the SAME ``fit()`` call drains,
   reshapes to 4 devices and finishes.  Asserted: trajectory equals the
   uninterrupted reference (preserve-global policy changes placement,
   not math), zero steps lost, the reshape record/topology, and that a
   fresh 4-device process resumes the survivor's checkpoints with a
   BIT-EXACT history continuation.

2. **Cross-process restart** (``--quick`` skips it): a REAL 2-process
   ``jax.distributed`` cluster (the mp_worker pattern) loses host 1 to
   a hard ``os._exit`` mid-step — no emergency checkpoint, the
   SIGKILL'd-pod-host case.  The driver reaps the survivor and restarts
   at a different topology (1 process, 2 devices) with
   ``fit(resume=True)``.  Asserted: completion, finite history, and
   steps-lost bounded by the ``save_every_steps`` cadence; the restart
   wall-clock is the ``time_to_recover_secs`` the bench gate ratchets.

Prints ``ELASTIC_SMOKE_RESULT {json}`` and exits non-zero on any
violation.
"""

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KILL_STEP = 6          # epoch 2, batch 2 of 4 (mid-epoch drain)
SAVE_EVERY = 2         # restart leg: step-checkpoint cadence = loss bound
MP_KILL_STEP = 6


# ----------------------------------------------------------- worker modes
def _worker_preamble(ndev: int):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)


def _make_trainer(workdir, ndev, **kw):
    from ml_trainer_tpu import MLModel, Trainer
    from ml_trainer_tpu.data import SyntheticCIFAR10

    if ndev is not None:
        kw["mesh_shape"] = {"data": ndev}  # else the default pod mesh
    return Trainer(
        MLModel(),
        datasets=(SyntheticCIFAR10(size=64, seed=0),
                  SyntheticCIFAR10(size=32, seed=1)),
        epochs=kw.pop("epochs", 3), batch_size=16, model_dir=workdir,
        metric=None, lr=0.01, seed=7, optimizer="adam", **kw,
    )


def worker_ref(workdir: str) -> int:
    _worker_preamble(8)
    t = _make_trainer(workdir, 8)
    t.fit()
    print(f"LOSSES {t.train_losses}", flush=True)
    return 0


def worker_chaos(workdir: str) -> int:
    _worker_preamble(8)
    os.environ["ML_TRAINER_TPU_FAULTS"] = (
        f"host_kill@step={KILL_STEP},host=1"
    )
    t = _make_trainer(workdir, 8, elastic=2)
    t.fit()
    assert not t.preempted, "elastic run exited preempted"
    assert int(t.mesh.size) == 4, f"mesh not reshaped: {t.mesh}"
    assert len(t.history["reshapes"]) == 1, t.history["reshapes"]
    rec = t.history["reshapes"][0]
    assert rec["old_topology"] == {"data": 8}, rec
    assert rec["new_topology"] == {"data": 4}, rec
    assert rec["steps_lost"] == 0, rec
    kinds = [r["kind"] for r in t._flight.records()]
    assert "reshape" in kinds, kinds
    from ml_trainer_tpu.telemetry import goodput

    assert goodput.snapshot()["reshape"] > 0.0, goodput.snapshot()
    print(f"RESHAPE {json.dumps(rec)}", flush=True)
    print(f"LOSSES {t.train_losses}", flush=True)
    return 0


def worker_resume(workdir: str) -> int:
    # A fresh process at the POST-reshape topology resumes the chaos
    # run's checkpoints: the reported history must be bit-exact.
    _worker_preamble(4)
    t = _make_trainer(workdir, 4, epochs=4)
    t.fit(resume=True)
    print(f"LOSSES {t.train_losses}", flush=True)
    return 0


def worker_mphost(port: str, pid: str, workdir: str) -> int:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["ML_TRAINER_TPU_FAULTS"] = (
        f"host_kill@step={MP_KILL_STEP},host=1"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=int(pid),
    )
    sys.path.insert(0, REPO)
    t = _make_trainer(
        workdir, None, epochs=2, save_every_steps=SAVE_EVERY,
        is_parallel=True, backend="cpu",
    )
    t.fit()  # host 1 never returns (os._exit inside the loop)
    print(f"LOSSES {t.train_losses}", flush=True)
    return 0


def worker_mpresume(workdir: str) -> int:
    _worker_preamble(2)
    from ml_trainer_tpu import checkpoint as ckpt

    latest = ckpt.latest_valid_checkpoint(
        os.path.join(workdir, "checkpoints"), quarantine=False
    )
    assert latest is not None, "no committed checkpoint survived the kill"
    with open(os.path.join(latest, "manifest.json")) as fp:
        manifest = json.load(fp)
    mid = (manifest.get("history") or {}).get("mid_epoch") or {}
    cursor = {
        "epoch": manifest.get("epoch"),
        "batches_done": mid.get("batches_done", 0),
        "mesh": manifest.get("mesh"),
    }
    print(f"CURSOR {json.dumps(cursor)}", flush=True)
    t = _make_trainer(workdir, 2, epochs=2)
    t.fit(resume=True)
    assert len(t.train_losses) == 2, t.train_losses
    print(f"LOSSES {t.train_losses}", flush=True)
    return 0


# ------------------------------------------------------------ orchestrator
def _spawn(args, env_extra=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("ML_TRAINER_TPU_FAULTS", None)
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )


def _run_phase(args, timeout=240):
    t0 = time.perf_counter()
    proc = _spawn(args)
    out, _ = proc.communicate(timeout=timeout)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(f"phase {args[0]} failed (rc={proc.returncode}):\n{out}")
    return out, dt


def _parse(out: str, tag: str):
    line = next(
        ln for ln in out.splitlines() if ln.startswith(tag + " ")
    )
    payload = line[len(tag) + 1:]
    return json.loads(payload) if payload.lstrip().startswith(
        ("{", "[")
    ) else eval(payload)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _close(a, b, rel=2e-4):
    return len(a) == len(b) and all(
        abs(x - y) <= rel * max(abs(x), abs(y), 1e-12) for x, y in zip(a, b)
    )


def leg_in_process(workdir: str) -> dict:
    ref_out, _ = _run_phase(["ref", os.path.join(workdir, "ref")])
    chaos_dir = os.path.join(workdir, "chaos")
    chaos_out, chaos_secs = _run_phase(["chaos", chaos_dir])
    resume_out, resume_secs = _run_phase(["resume", chaos_dir])
    ref = _parse(ref_out, "LOSSES")
    chaos = _parse(chaos_out, "LOSSES")
    reshape = _parse(chaos_out, "RESHAPE")
    resumed = _parse(resume_out, "LOSSES")
    traj_equal = _close(chaos, ref)
    # Bit-exact-resumable: the 4-device process re-reports the chaos
    # run's history from its checkpoints EXACTLY, then extends it.
    resumable = len(resumed) == 4 and resumed[:3] == chaos
    return {
        "ok": bool(
            traj_equal and resumable and reshape["steps_lost"] == 0
        ),
        "trajectory_equal": traj_equal,
        "bit_exact_resumable": resumable,
        "steps_lost": reshape["steps_lost"],
        "reshape_downtime_secs": reshape["downtime_secs"],
        "old_topology": reshape["old_topology"],
        "new_topology": reshape["new_topology"],
        "trigger": reshape["trigger"],
        "chaos_run_secs": round(chaos_secs, 2),
        "resume_run_secs": round(resume_secs, 2),
        "losses": {"ref": ref, "chaos": chaos, "resumed": resumed},
    }


def leg_restart(workdir: str) -> dict:
    port = _free_port()
    mp_dir = os.path.join(workdir, "mp")
    procs = [
        _spawn(["mphost", str(port), str(pid), mp_dir]) for pid in (0, 1)
    ]
    victim = procs[1]
    try:
        victim.communicate(timeout=180)
    except subprocess.TimeoutExpired:
        victim.kill()
        victim.communicate(timeout=10)
        raise RuntimeError("host 1 did not die on its host_kill fault")
    if victim.returncode != 113:
        out0, _ = procs[0].communicate(timeout=10)
        raise RuntimeError(
            f"host 1 exited rc={victim.returncode}, expected the "
            f"host_kill hard-exit 113\n{out0}"
        )
    # The survivor blocks in a collective its peer never joins (or dies
    # on a gloo error) — the driver's correlated teardown is the
    # real-world whole-job SIGKILL.
    try:
        procs[0].communicate(timeout=8)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].communicate(timeout=10)
    t0 = time.perf_counter()
    out, _ = _run_phase(["mpresume", mp_dir], timeout=240)
    recover_secs = time.perf_counter() - t0
    cursor = _parse(out, "CURSOR")
    losses = _parse(out, "LOSSES")
    steps_per_epoch = 4  # 64 samples / global batch 16
    committed = (
        int(cursor["epoch"]) * steps_per_epoch
        if not cursor["batches_done"]
        else (int(cursor["epoch"]) - 1) * steps_per_epoch
        + int(cursor["batches_done"])
    )
    steps_lost = (MP_KILL_STEP - 1) - committed  # the kill pre-empted step 6
    finite = all(
        isinstance(v, float) and v == v and abs(v) != float("inf")
        for v in losses
    )
    return {
        "ok": bool(
            0 <= steps_lost <= SAVE_EVERY and len(losses) == 2 and finite
        ),
        "steps_lost": steps_lost,
        "steps_lost_bound": SAVE_EVERY,
        "committed_steps": committed,
        "kill_step": MP_KILL_STEP,
        "saved_mesh": cursor.get("mesh"),
        "time_to_recover_secs": round(recover_secs, 2),
        "losses": losses,
    }


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        mode, args = sys.argv[2], sys.argv[3:]
        return {
            "ref": worker_ref,
            "chaos": worker_chaos,
            "resume": worker_resume,
            "mphost": worker_mphost,
            "mpresume": worker_mpresume,
        }[mode](*args)
    quick = "--quick" in sys.argv[1:]
    import tempfile

    workdir = tempfile.mkdtemp(prefix="elastic_smoke_")
    result = {"in_process": leg_in_process(workdir)}
    if not quick:
        result["restart"] = leg_restart(workdir)
    result["ok"] = all(
        leg["ok"] for leg in result.values() if isinstance(leg, dict)
    )
    print(f"ELASTIC_SMOKE_RESULT {json.dumps(result)}", flush=True)
    if not result["ok"]:
        print("ELASTIC_SMOKE FAIL", flush=True)
        return 1
    ip = result["in_process"]
    msg = (
        f"ELASTIC_SMOKE OK: reshape {ip['old_topology']} -> "
        f"{ip['new_topology']} mid-run, trajectory equal, history "
        f"bit-exact-resumable, {ip['steps_lost']} step(s) lost"
    )
    if "restart" in result:
        rs = result["restart"]
        msg += (
            f"; hard-kill restart lost {rs['steps_lost']} step(s) "
            f"(bound {rs['steps_lost_bound']}), recovered in "
            f"{rs['time_to_recover_secs']}s"
        )
    print(msg, flush=True)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
