#!/usr/bin/env python
"""Overload/failure-survival smoke leg (scripts/fastlane.sh) — ~90s on CPU.

One short end-to-end pass over the overload stack (serving/overload.py,
serving/autoscaler.py, the hardened router), on a real HTTP fleet:

1. **Breaker opens on an injected wedge.**  A decode replica's engine
   wedges (``decode_wedge`` fault); the watchdog fails its streams, the
   router's redistribute records the failure, the per-replica circuit
   breaker OPENS without waiting for the health poller — and the
   redistributed stream finishes byte-identical on the survivor.
2. **Ladder engages and exits.**  Rung 3 (hits_only) sheds a fresh
   prefix-cache miss over HTTP with a STRUCTURED 503 + retry_after
   (body and header); stepping back to rung 0 serves the same request
   fine.  ``serving_degradation_level`` tracks on ``/metrics``.
3. **Autoscaler adds a replica under burn.**  A decode replica is
   killed; the SLO-burn autoscaler's repair rule adds a replacement
   (``auto1``) and the fleet serves again.
4. **Observability.**  The router ``/metrics`` scrape carries
   ``serving_degradation_level``, ``router_hedges_total``,
   ``router_breaker_state{replica=}``, ``router_flaps_damped_total``
   and ``autoscaler_actions_total{action=}``.

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"OVERLOAD_SMOKE FAIL: {msg}")
    return 1


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.serving import (
        Autoscaler,
        AutoscalerConfig,
        Router,
        Server,
    )

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    prompts = [
        np.asarray(rng.integers(0, 1024, n), np.int32)
        for n in (9, 6, 12, 8)
    ]
    long_refs = [
        np.asarray(generate(model, variables, p[None], 28))[0]
        for p in prompts
    ]

    # Warm the serving programs (prefill buckets, decode, kv
    # export/import) with a default-watchdog fleet: the wedge leg runs
    # a 2s watchdog, which first-hit XLA compiles on the loop thread
    # would trip spuriously.
    with Router.build(model, variables, roles=["prefill", "decode"],
                      max_batch=2, kv_page_size=8) as router:
        for p in prompts[:2]:
            router.complete(p, 28, timeout=300)

    # 1: decode_wedge -> watchdog -> breaker OPEN -> byte-identical
    # redistribute.  Short watchdog so the wedge is detected fast.
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=8,
                      watchdog_timeout=2.0,
                      router_kwargs={"breaker_threshold": 1},
                      ) as router:
        with faults.injected("decode_wedge@step=3,secs=30") as plan:
            streams = [router.submit(p, 28) for p in prompts]
            outs = [np.asarray(s.result(timeout=300)) for s in streams]
            plan.release_wedge()
        snap = router.snapshot()
        breaker_states = {
            name: rep.breaker.state
            for name, rep in router.replicas.items()
        }
    for out, ref in zip(outs, long_refs):
        if not np.array_equal(out, ref):
            return fail("post-wedge redistributed output diverged")
    if snap["redistributes_total"] < 1:
        return fail("wedge produced no redistribution")
    if "open" not in breaker_states.values():
        return fail(f"no breaker opened on the wedge: {breaker_states}")
    print(f"# overload smoke: wedge -> breaker open "
          f"({ {n: s for n, s in breaker_states.items() if s != 'closed'} }), "
          f"{snap['redistributes_total']} redistribute(s), byte-identical")

    # 2+3+4: ladder engage/exit over HTTP, autoscaler repair, metrics.
    shared = np.asarray(rng.integers(0, 1024, 20), np.int32)
    miss = np.asarray(rng.integers(0, 1024, 20), np.int32)
    with Router.build(model, variables,
                      roles=["prefill", "decode", "decode"],
                      max_batch=2, kv_page_size=8) as router:
        asc = Autoscaler(
            router,
            lambda role: Server(model, variables, max_batch=2,
                                kv_page_size=8, role=role),
            AutoscalerConfig(poll_interval_s=0.2, min_decode=2),
        ).start()
        try:
            host, port = router.serve_http(port=0)
            url = f"http://{host}:{port}"

            def post(prompt, n=3, expect=200):
                body = json.dumps({
                    "prompt": [int(t) for t in prompt],
                    "max_new_tokens": n,
                }).encode()
                req = urllib.request.Request(
                    f"{url}/v1/generate", data=body,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=300) as r:
                        return r.status, json.loads(r.read()), dict()
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read()), dict(e.headers)

            code, payload, _ = post(shared)       # primes the cache
            if code != 200:
                return fail(f"warm request failed: {code} {payload}")
            router.ladder.set_level(3, "smoke burn")
            code, payload, headers = post(miss)
            if code != 503:
                return fail(f"hits_only miss not shed: {code} {payload}")
            if "retry_after" not in payload or "hits_only" not in \
                    payload.get("error", ""):
                return fail(f"shed 503 not structured: {payload}")
            if "Retry-After" not in headers:
                return fail(f"shed 503 missing Retry-After: {headers}")
            code, payload, _ = post(
                np.concatenate([shared[:16], prompts[1][:4]])
            )
            if code != 200:
                return fail(f"prefix HIT shed under hits_only: {code} "
                            f"{payload}")
            router.ladder.set_level(0, "smoke recovered")
            code, payload, _ = post(miss)
            if code != 200:
                return fail(f"ladder did not exit: {code} {payload}")
            print("# overload smoke: ladder rung 3 shed a miss with "
                  "structured 503 + Retry-After, served the hit, and "
                  "exited clean")

            # Autoscaler repair: kill a decode replica, wait for auto1.
            router.kill_replica("decode0")
            deadline = time.monotonic() + 30
            while "auto1" not in router.replicas:
                if time.monotonic() > deadline:
                    return fail("autoscaler never replaced the dead "
                                "replica")
                time.sleep(0.05)
            code, payload, _ = post(prompts[0], n=4)
            if code != 200:
                return fail(f"post-repair request failed: {code} "
                            f"{payload}")
            asc.publish()
            with urllib.request.urlopen(
                f"{url}/metrics", timeout=30
            ) as resp:
                prom = resp.read().decode()
        finally:
            asc.close()
    for needle in (
        "serving_degradation_level",
        "router_hedges_total",
        "router_flaps_damped_total",
        'router_breaker_state{replica="decode0"}',
        'autoscaler_actions_total{action="scale_up"}',
        "autoscaler_replicas{",
    ):
        if needle not in prom:
            return fail(f"{needle!r} missing from /metrics scrape")
    print("# overload smoke: autoscaler replaced the dead replica "
          "(auto1) and every overload series is on /metrics")
    print("OVERLOAD_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
