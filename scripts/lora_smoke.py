#!/usr/bin/env python
"""Batched-LoRA serving smoke leg (scripts/fastlane.sh) — ~90s on CPU.

One short end-to-end pass over the batched-adapter stack
(serving/adapter_pool.py + the per-row lora decode path) through the
REAL HTTP server:

1. **8 adapters + base traffic interleaved.**  A seeded open-loop
   schedule draws each request's adapter from {None, a0..a7}; every
   request completes over POST ``/v1/generate`` with its ``"adapter"``
   field.
2. **Byte identity for adapter=None.**  The base requests' outputs are
   byte-identical to ``generate()`` on the base model — the trash
   slot 0 zero-delta contract, through the full HTTP path.
3. **Isolation.**  The same shared-prefix prompt served under two
   different adapters and the base yields three DIFFERENT outputs, the
   base one equal to the reference — and the prefix cache records a
   MISS for the cross-adapter probe.
4. **Hot-load under load.**  A never-registered adapter loads WHILE
   streams are decoding and serves immediately — with ZERO compiled
   programs minted after warmup (rank bucket + warm upload program).
5. **Gauges.**  ``/metrics`` exposes
   ``serving_adapter_pool_bytes{state=...}`` and the
   ``serving_adapter_{hits,loads,evictions}_total`` series;
   ``/healthz`` advertises ``adapters_resident``.

Exits non-zero (with a reason) on any violation.
"""

import json
import os
import sys
import tempfile
import threading
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> int:
    print(f"LORA_SMOKE FAIL: {msg}")
    return 1


def post(url: str, payload: dict, timeout: float = 300.0) -> dict:
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{url}/v1/generate", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def main() -> int:
    import jax

    from ml_trainer_tpu.generate import _COMPILED, generate
    from ml_trainer_tpu.lora import LoraConfig, export_lora_artifact
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import AdapterConfig, Server

    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="lora_smoke_")
    targets = ("qkv", "proj")

    def make_artifact(name, rank):
        lm = model.clone(lora_rank=rank, lora_alpha=float(2 * rank),
                         lora_targets=targets)
        params = jax.device_get(lm.init(
            {"params": jax.random.PRNGKey(1)},
            np.zeros((1, 8), np.int32), train=False,
        )["params"])

        def bump(node):
            return {
                k: (bump(v) if hasattr(v, "items")
                    else rng.standard_normal(v.shape).astype(np.float32)
                    if "_lora_B" in k else v)
                for k, v in node.items()
            }

        path = os.path.join(tmp, f"{name}.npz")
        export_lora_artifact(
            bump(dict(params)),
            LoraConfig(rank=rank, alpha=float(2 * rank), targets=targets),
            path, name=name,
        )
        return path

    names = [f"a{i}" for i in range(8)]
    sources = {
        n: make_artifact(n, 4 if i % 2 else 8)
        for i, n in enumerate(names)
    }
    hot_path = make_artifact("hot", 8)

    prompts = [rng.integers(0, 1024, 5 + i % 7).astype(np.int32)
               for i in range(12)]
    shared = np.concatenate([
        rng.integers(0, 1024, 16).astype(np.int32),
        rng.integers(0, 1024, 3).astype(np.int32),
    ])
    # The isolation probe runs on a prompt NO namespace has seen (same
    # length as ``shared``, whose warmup covered the bucket) so the
    # cross-adapter MISS is unambiguous.
    shared2 = np.concatenate([
        rng.integers(0, 1024, 16).astype(np.int32),
        rng.integers(0, 1024, 3).astype(np.int32),
    ])
    refs = [np.asarray(generate(model, variables, p[None], 5))[0]
            for p in prompts]
    shared2_ref = np.asarray(generate(model, variables, shared2[None], 5))[0]

    with Server(model, variables, max_batch=4, max_queue=64,
                kv_page_size=8,
                adapters=AdapterConfig(slots=12, rank=8, targets=targets,
                                       sources=sources)) as srv:
        host, port = srv.serve_http(port=0)
        url = f"http://{host}:{port}"

        # Warmup: TWO passes over every shape the smoke will drive —
        # all prompt buckets x {base, adapters}, the shared prompt's
        # bucket, and (pass 2, now that pass 1 populated the prefix
        # cache) the paged continuation buckets a prefix hit runs.
        for _ in range(2):
            for i, p in enumerate(prompts):
                post(url, {"prompt": [int(t) for t in p],
                           "max_new_tokens": 5,
                           "adapter": names[i % 8] if i % 3 else None})
                post(url, {"prompt": [int(t) for t in p],
                           "max_new_tokens": 5,
                           "adapter": names[i % 8] if i % 2 else None})
            for adapter in (None, "a0", "a1"):
                post(url, {"prompt": [int(t) for t in shared],
                           "max_new_tokens": 5, "adapter": adapter})
            for j, n in enumerate(names):  # every adapter resident
                post(url, {"prompt": [int(t) for t in prompts[j]],
                           "max_new_tokens": 5, "adapter": n})
        n_warm = len(_COMPILED._data)

        # 1+2: interleaved base + 8-adapter traffic, byte identity for
        # the base rows.
        outs = []
        for i, p in enumerate(prompts):
            adapter = names[i % 8] if i % 2 else None
            outs.append((adapter, post(
                url, {"prompt": [int(t) for t in p], "max_new_tokens": 5,
                      "adapter": adapter})["tokens"]))
        for (adapter, out), ref in zip(outs, refs):
            if adapter is None and out != [int(t) for t in ref]:
                return fail("adapter=None HTTP output diverged from "
                            "generate() on the base model")

        # 3: isolation on a fresh shared-prefix prompt.
        eng = srv.engine
        out_base = post(url, {"prompt": [int(t) for t in shared2],
                              "max_new_tokens": 5})["tokens"]
        misses0 = eng._prefix.misses
        out_a = post(url, {"prompt": [int(t) for t in shared2],
                           "max_new_tokens": 5, "adapter": "a0"})["tokens"]
        if eng._prefix.misses != misses0 + 1:
            return fail("cross-adapter probe of a cached prompt did not "
                        "MISS (namespace leak)")
        out_b = post(url, {"prompt": [int(t) for t in shared2],
                           "max_new_tokens": 5, "adapter": "a1"})["tokens"]
        if out_base != [int(t) for t in shared2_ref]:
            return fail("base output on the shared prompt diverged")
        if out_a == out_base or out_b == out_base or out_a == out_b:
            return fail("adapter outputs did not separate "
                        f"(base={out_base[-3:]}, a0={out_a[-3:]}, "
                        f"a1={out_b[-3:]})")

        # 4: hot-load while streams are decoding.
        streams = [srv.submit(prompts[i], 12, adapter=names[i % 8])
                   for i in range(3)]
        hot_out = {}

        def load_and_serve():
            srv.load_adapter("hot", hot_path)
            hot_out["tokens"] = post(
                url, {"prompt": [int(t) for t in prompts[0]],
                      "max_new_tokens": 5, "adapter": "hot"})["tokens"]

        t = threading.Thread(target=load_and_serve)
        t.start()
        for s in streams:
            s.result(timeout=300)
        t.join(timeout=300)
        if not hot_out.get("tokens"):
            return fail("hot-loaded adapter served nothing under load")
        n_after = len(_COMPILED._data)
        if n_after != n_warm:
            return fail(
                f"{n_after - n_warm} program(s) minted after warmup — "
                "adapter traffic/hot-load must never recompile"
            )

        # 5: gauges + health.
        with urllib.request.urlopen(f"{url}/metrics", timeout=30) as resp:
            prom = resp.read().decode()
        with urllib.request.urlopen(f"{url}/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
    for series in (
        'serving_adapter_pool_bytes{state="used"}',
        "serving_adapter_hits_total",
        "serving_adapter_loads_total",
        "serving_adapter_evictions_total",
    ):
        if series not in prom:
            return fail(f"{series} missing from /metrics")
    resident = health.get("adapters_resident") or []
    if "hot" not in resident or len(resident) < 9:
        return fail(f"/healthz adapters_resident wrong: {resident}")
    print(f"# lora smoke: 8 adapters + base interleaved, isolation held, "
          f"hot-load served {len(hot_out['tokens'])} ids, "
          f"{len(resident)} resident, 0 new programs after warmup")
    print("LORA_SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
