"""Author + execute the three user-workflow notebooks (SURVEY.md §1 L3).

The reference ships its workflow as notebooks with committed outputs
(01_ML_Training_local / 02_ML_Training_SageMaker_distributed /
03_ML_Testing); this script generates the TPU-native equivalents in
``notebooks/`` and executes them so the committed .ipynb files carry real
outputs — the golden-run record in notebook form.

    python scripts/make_notebooks.py            # author + execute all three
    python scripts/make_notebooks.py --no-exec  # author only

02 executes in CPU-mesh rehearsal mode (8 virtual devices — the analog of
the reference's SageMaker local_gpu/gloo path, SURVEY.md §4); on a real
multi-host TPU slice the same cells run unchanged.
"""

import argparse
import os
import sys

import nbformat as nbf

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "notebooks")


def _rehearsal_cell(default: str, devices: int = 0) -> str:
    """One shared backend-guard cell for all three notebooks.

    ``default`` — "1" for notebooks whose committed form runs rehearsed
    (02: the multi-chip flow needs a virtual mesh in this 1-chip
    environment), "0" for notebooks meant to run on the chip (01/03;
    NB_REHEARSAL=1 is their TPU-down fallback, and the committed outputs
    record whichever backend actually ran — check the cell output).
    ``devices`` > 0 also forces that many virtual host-CPU devices."""
    flags = ""
    if devices:
        flags = f"""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count={devices}"
    ).strip()"""
    return f"""
import os
# Rehearsal mode (NB_REHEARSAL={default} here): pin the host-CPU backend.
# On a real TPU host set NB_REHEARSAL=0 and the mesh picks up the chips;
# the cell's output below records which backend this notebook really ran.
if os.environ.get("NB_REHEARSAL", "{default}") == "1":
    os.environ["JAX_PLATFORMS"] = "cpu"{flags}
import jax
if os.environ.get("NB_REHEARSAL", "{default}") == "1":
    # jax may already be imported by interpreter-startup site hooks with a
    # TPU platform pinned; the config override wins (backends init lazily).
    jax.config.update("jax_platforms", "cpu")
jax.devices()
"""


def _nb(cells):
    nb = nbf.v4.new_notebook()
    nb.metadata.kernelspec = {
        "display_name": "Python 3", "language": "python", "name": "python3",
    }
    out = []
    for kind, src in cells:
        cell = (
            nbf.v4.new_markdown_cell(src.strip())
            if kind == "md"
            else nbf.v4.new_code_cell(src.strip())
        )
        out.append(cell)
    nb.cells = out
    return nb


NB01 = [
    ("md", """
# Local training — TPU-native

The `01_ML_Training_local` flow on a TPU chip: build datasets → config →
`Trainer(epochs=6, batch_size=32)` → `fit()` → save/load/plot history →
`load_model` → `test()`.  Same public surface as the reference
(`src/trainer.py:22-311`), internals are one compiled XLA step.
"""),
    ("code", _rehearsal_cell(default="0")),
    ("code", """
from ml_trainer_tpu import (
    MLModel, Loader, Trainer, load_history, load_model, plot_history,
)
from ml_trainer_tpu.data import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function
"""),
    ("code", """
# Real CIFAR-10 when the pickle batches are on disk, synthetic otherwise
# (this environment has no egress).
transform = custom_pre_process_function()
try:
    datasets = (CIFAR10("data", train=True, transform=transform),
                CIFAR10("data", train=False, transform=transform))
except FileNotFoundError:
    datasets = (SyntheticCIFAR10(size=2048, transform=transform),
                SyntheticCIFAR10(size=512, transform=transform, seed=1))
len(datasets[0]), len(datasets[1])
"""),
    ("code", """
# Label distribution (the reference notebook's class histogram cell).
import numpy as np
targets = np.asarray(datasets[0].targets)
dict(zip(*np.unique(targets, return_counts=True)))
"""),
    ("code", """
# A few training images after augmentation (reference image-grid cell).
import matplotlib.pyplot as plt
fig, axes = plt.subplots(2, 4, figsize=(8, 4))
for i, ax in enumerate(axes.flat):
    x, y = datasets[0][i]
    ax.imshow((np.asarray(x) * 0.25 + 0.5).clip(0, 1))
    ax.set_title(int(y)); ax.axis("off")
plt.tight_layout()
"""),
    ("code", """
config = {
    "seed": 32,
    "scheduler": "CosineAnnealingWarmRestarts",
    "optimizer": "sgd",
    "momentum": 0.9,
    "weight_decay": 0.0,
    "lr": 0.001,
    "criterion": "cross_entropy",
    "metric": "accuracy",
    "pred_function": "softmax",
    "model_dir": "model_output",
}
trainer = Trainer(MLModel(), datasets=datasets, epochs=6, batch_size=32,
                  save_history=True, **config)
"""),
    ("code", "trainer.fit()"),
    ("code", """
history = load_history("model_output")
{k: (v[-1] if isinstance(v, list) else v) for k, v in history.items()}
"""),
    ("code", "plot_history(history)"),
    ("code", """
loaded = load_model(MLModel(), "model_output")
test_loader = Loader(datasets[1], batch_size=32, shuffle=True)
test_loss, test_acc = trainer.test(loaded, test_loader)
print(f"test loss {test_loss:.4f}  accuracy {test_acc:.4f}")
"""),
]

NB02 = [
    ("md", """
# Distributed data-parallel training — TPU-native

Where the reference provisions SageMaker GPU instances and launches
`main.py` under SMDDP (02 nb cells 4-7), the TPU path is **one command per
TPU VM host** — `jax.distributed` auto-detects the slice and the mesh spans
every chip.  This notebook runs the same cells in CPU-mesh rehearsal mode
(8 virtual devices — the analog of the reference's `local_gpu`/gloo
rehearsal) so the full distributed path executes anywhere; on a TPU slice
the environment cell is a no-op and the mesh picks up the real chips.
"""),
    ("code", _rehearsal_cell(default="1", devices=8)),
    ("code", """
from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.parallel import rules_for
from ml_trainer_tpu.utils.functions import custom_pre_process_function

transform = custom_pre_process_function()
datasets = (SyntheticCIFAR10(size=4096, transform=transform),
            SyntheticCIFAR10(size=512, transform=transform, seed=1))
"""),
    ("code", """
# The reference's hyperparameters dict (02 nb cell-4), same keys; `backend`
# aliases smddp -> the TPU mesh backend (config.py).
config = {
    "seed": 32,
    "optimizer": "sgd",
    "momentum": 0.9,
    "lr": 0.01,
    "criterion": "cross_entropy",
    "metric": "accuracy",
    "pred_function": "softmax",
    "model_dir": "model_output_distributed",
    "backend": "smddp",
}
"""),
    ("code", """
# Pure DP over every device; set TP=2 for a dp*tp Megatron-sharded mesh —
# the knob the estimator's distribution dict never had.
TP = int(os.environ.get("TP", "1"))
mesh_shape = ({"data": jax.device_count() // TP, "tensor": TP}
              if TP > 1 else None)
sharding_rules = rules_for("resnet18", "tp") if TP > 1 else None
trainer = Trainer(get_model("resnet18"), datasets=datasets, epochs=2,
                  batch_size=256, is_parallel=True, save_history=True,
                  mesh_shape=mesh_shape, sharding_rules=sharding_rules,
                  **config)
trainer.mesh
"""),
    ("code", "trainer.fit()"),
    ("code", """
from ml_trainer_tpu import load_history
history = load_history("model_output_distributed")
{k: (v[-1] if isinstance(v, list) else v) for k, v in history.items()}
"""),
]

NB03 = [
    ("md", """
# Testing / inference-only — TPU-native

The `03_ML_Testing` flow: build a test loader → `load_model` → a
**dataset-less Trainer** (the "Testing only available" path, ref:
`src/trainer.py:66-71`) → `trainer.test(model, loader)`.  `load_model`
also accepts a reference torch `model.pth` (the `module.`-prefix-tolerant
import with OIHW→HWIO conversion, ref: `src/utils/utils.py:15-28`).
"""),
    ("code", _rehearsal_cell(default="0")),
    ("code", """
from ml_trainer_tpu import MLModel, Loader, Trainer, load_model
from ml_trainer_tpu.data import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function

transform = custom_pre_process_function()
try:
    val_set = CIFAR10("data", train=False, transform=transform)
except FileNotFoundError:
    val_set = SyntheticCIFAR10(size=512, transform=transform, seed=1)
test_loader = Loader(val_set, batch_size=32, shuffle=True)
"""),
    ("code", 'model = load_model(MLModel(), "model_output")  # .msgpack dir or torch .pth'),
    ("code", "trainer = Trainer(MLModel())  # no datasets: inference-only trainer"),
    ("code", """
test_loss, test_metric = trainer.test(model, test_loader)
print(f"loss {test_loss:.4f}  accuracy {test_metric:.4f}")
"""),
]


def build(execute=True, only=None):
    os.makedirs(OUT, exist_ok=True)
    books = {
        "01_ML_Training_local.ipynb": NB01,
        "02_ML_Training_distributed.ipynb": NB02,
        "03_ML_Testing.ipynb": NB03,
    }
    for name, cells in books.items():
        if only and only not in name:
            continue
        nb = _nb(cells)
        path = os.path.join(OUT, name)
        if execute:
            from nbclient import NotebookClient

            print(f"executing {name} ...", flush=True)
            client = NotebookClient(
                nb, timeout=1800, kernel_name="python3",
                resources={"metadata": {"path": ROOT}},
            )
            client.execute()
        nbf.write(nb, path)
        print(f"wrote {path}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-exec", action="store_true")
    ap.add_argument("--only", default=None, help="substring filter")
    args = ap.parse_args()
    build(execute=not args.no_exec, only=args.only)
    sys.exit(0)
