"""Flash-attention block-size sweep on the chip.

The kernel's ``block_q``/``block_k`` default to 128×128 — chosen for
tile legality, never measured.  This sweeps the grid over the GPT-2
north-star shape (and any ``--shape``), timing forward and
forward+backward per geometry, and records the table + the best choice
to docs/flash_block_tune.json.  If a non-default geometry wins by more
than ~5%, ops/attention.py's defaults should follow the data.

    python scripts/flash_tune.py
    python scripts/flash_tune.py --shape 8,12,1024,64 --blocks 128,256,512

``--paged`` sweeps the paged-attention DECODE kernel instead
(ops/kernels/paged_attention.py): the tunable geometry there is the
page size — each grid step fetches one [page, D] K/V block per
BlockSpec index_map, so the page size IS the kernel's block height.
Each row fixes the total context L and varies page_size (the pool's
``kv_page_size`` knob), timing the fused kernel against the gather+
attention reference at batch-decode shape; the table + best page size
land in docs/paged_decode_tune.json.

    python scripts/flash_tune.py --paged
    python scripts/flash_tune.py --paged --paged-shape 8,12,64,1024 \
        --page-sizes 8,16,32,64,128
"""

import argparse
import itertools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ml_trainer_tpu.ops.attention import flash_attention  # noqa: E402
# ONE definition of the data-dependent chained timing harness (in-order
# completion cannot be assumed on this platform): reuse it, never fork it.
from validate_flash_tpu import bench  # noqa: E402


def run_paged(args) -> None:
    """Page-size sweep for the fused paged-attention decode kernel at a
    batch-decode shape: one [B, H, D] query row against L cached tokens
    scattered across pages.  Rows without the chip never run (the
    caller asserts the backend) — off-TPU parity is tests/'s job."""
    from ml_trainer_tpu.ops.kernels.paged_attention import (
        paged_attention,
        paged_attention_reference,
    )

    b, h, d, L = (int(x) for x in args.paged_shape.split(","))
    dtype = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, h, d)) * 0.5, dtype)
    lengths = jnp.asarray(
        rng.integers(1, L + 1, size=b), jnp.int32
    ).at[0].set(L)  # one full row so every sweep touches all pages

    rows = []
    for ps in (int(x) for x in args.page_sizes.split(",")):
        if L % ps:
            continue
        P = L // ps
        n_pages = b * P + 1  # + trash page 0
        k_pool, v_pool = (
            jnp.asarray(rng.normal(size=(n_pages, h, ps, d)) * 0.5, dtype)
            for _ in range(2)
        )
        table = jnp.asarray(
            1 + rng.permutation(n_pages - 1).reshape(b, P), jnp.int32
        )

        def kern(q, kp, vp, tb, ln):
            return paged_attention(q, kp, vp, tb, ln,
                                   implementation="pallas")

        def ref(q, kp, vp, tb, ln):
            return paged_attention_reference(q, kp, vp, tb, ln)

        try:
            row = {
                "page_size": ps, "pages_per_seq": P,
                "kernel_ms": round(bench(
                    jax.jit(kern), q, k_pool, v_pool, table, lengths
                ) * 1e3, 3),
                "reference_ms": round(bench(
                    jax.jit(ref), q, k_pool, v_pool, table, lengths
                ) * 1e3, 3),
            }
            row["speedup"] = round(
                row["reference_ms"] / max(row["kernel_ms"], 1e-9), 3
            )
        except Exception as e:  # geometry rejected by Mosaic (VMEM etc.)
            row = {"page_size": ps, "pages_per_seq": P,
                   "error": str(e).splitlines()[0][:160]}
        rows.append(row)
        print(json.dumps(row), flush=True)

    timed = [r for r in rows if "kernel_ms" in r]
    best = min(timed, key=lambda r: r["kernel_ms"]) if timed else None
    record = {
        "device": str(jax.devices()[0]),
        "shape": {"batch": b, "heads": h, "head_dim": d, "context": L},
        "dtype": str(dtype),
        "rows": rows, "best": best,
    }
    out = os.path.join(ROOT, "docs", "paged_decode_tune.json")
    with open(out, "w") as fp:
        json.dump(record, fp, indent=1)
    print(f"-> {out} best={best}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="8,12,1024,64",
                    help="B,H,S,D (default: the GPT-2 124M bench shape)")
    ap.add_argument("--blocks", default="128,256,512")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--paged", action="store_true",
                    help="sweep the paged-attention decode kernel's page "
                    "size instead of the flash block geometry")
    ap.add_argument("--paged-shape", default="8,12,64,1024",
                    help="B,H,D,L for --paged (default: GPT-2 124M "
                    "decode at 1024 cached tokens)")
    ap.add_argument("--page-sizes", default="8,16,32,64,128",
                    help="page sizes swept by --paged")
    args = ap.parse_args()
    from ml_trainer_tpu.utils.tunnel import acquire_tunnel_lock

    if not acquire_tunnel_lock(time.time() + 300.0, [],
                               label="flash_tune.py"):
        sys.exit("tunnel lock held by another client; try again later")
    assert jax.default_backend() == "tpu", (
        f"needs the chip, got {jax.default_backend()}"
    )
    if args.paged:
        run_paged(args)
        return
    b, h, s, d = (int(x) for x in args.shape.split(","))
    blocks = [int(x) for x in args.blocks.split(",")]
    dtype = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, h, s, d)) * 0.5, dtype)
        for _ in range(3)
    )

    rows = []
    for bq, bk in itertools.product(blocks, blocks):
        if s % bq or s % bk:
            continue

        def fwd(q, k, v, _bq=bq, _bk=bk):
            return flash_attention(q, k, v, None, True, None, _bq, _bk)

        def loss(q, k, v, _bq=bq, _bk=bk):
            return flash_attention(
                q, k, v, None, True, None, _bq, _bk
            ).sum().astype(jnp.float32)

        grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            row = {
                "block_q": bq, "block_k": bk,
                "fwd_ms": round(bench(jax.jit(fwd), q, k, v) * 1e3, 3),
                "fwd_bwd_ms": round(bench(grad, q, k, v) * 1e3, 3),
            }
        except Exception as e:  # geometry rejected by Mosaic (VMEM etc.)
            row = {"block_q": bq, "block_k": bk,
                   "error": str(e).splitlines()[0][:160]}
        rows.append(row)
        print(json.dumps(row), flush=True)

    timed = [r for r in rows if "fwd_bwd_ms" in r]
    best = min(timed, key=lambda r: r["fwd_bwd_ms"]) if timed else None
    record = {
        "device": str(jax.devices()[0]),
        "shape": [b, h, s, d], "dtype": str(dtype),
        "rows": rows, "best": best,
        "default": {"block_q": 128, "block_k": 128},
    }
    out = os.path.join(ROOT, "docs", "flash_block_tune.json")
    with open(out, "w") as fp:
        json.dump(record, fp, indent=1)
    print(f"-> {out} best={best}")


if __name__ == "__main__":
    main()
