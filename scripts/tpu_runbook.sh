#!/bin/bash
# Serialized TPU session: everything this repo needs from the (single,
# flaky) TPU chip, one process at a time — concurrent clients wedge the
# remote tunnel.  Stage commands and completion checks live in
# tpu_recover.sh (resume-aware: a fresh environment runs every stage, a
# wedged-session retry runs only what is still missing); this wrapper
# exists because the runbook name is the documented entry point.
exec bash "$(dirname "$0")/tpu_recover.sh" "$@"
