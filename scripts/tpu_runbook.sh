#!/bin/bash
# Serialized TPU session: everything this repo needs from the (single,
# flaky) TPU chip, one process at a time — concurrent clients wedge the
# remote tunnel.  Each stage logs to /tmp/tpu_runbook/.
set -u
cd "$(dirname "$0")/.."
# examples/ and scripts/ import the package from the repo root; running
# them as `python examples/01_...py` puts examples/ (not the root) on
# sys.path, so export the root explicitly.
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
OUT=/tmp/tpu_runbook
mkdir -p "$OUT" tests/golden

echo "== probe =="
timeout 240 python -u -c "import jax; print(jax.devices())" || {
  echo "TPU unavailable; aborting runbook"; exit 1; }

echo "== 1. headline bench (per-batch vs multi-step reconciliation) =="
# In-process watchdog BELOW the shell timeout so a hang still emits the
# safety JSON line before SIGTERM (the driver needs a parseable record).
BENCH_WATCHDOG_SECS=1500 timeout 1700 \
  python bench.py --reconcile | tee "$OUT/bench_headline.out"

echo "== 2. extended bench (budgeted) =="
BENCH_WATCHDOG_SECS=2800 EXTENDED_BUDGET_SECS=1800 timeout 3000 \
  python bench.py --extended 2>&1 | tee "$OUT/bench_extended.out"

echo "== 3. golden-run capture =="
GOLDEN_OUT=tests/golden/local_run_tpu.json MODEL_DIR=/tmp/golden_model \
  timeout 1800 python examples/01_local_training.py 2>&1 | tail -5 \
  | tee "$OUT/golden.out"

echo "== 4. flash-attention TPU validation =="
timeout 1800 python scripts/validate_flash_tpu.py 2>&1 | tail -8 \
  | tee "$OUT/flash.out"

echo "== 5. notebooks 01 + 03 (executed on TPU) =="
MODEL_DIR=model_output timeout 1800 python scripts/make_notebooks.py --only 01 \
  | tee "$OUT/nb01.out"
timeout 900 python scripts/make_notebooks.py --only 03 | tee "$OUT/nb03.out"

echo "== runbook done =="
